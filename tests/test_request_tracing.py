"""Request-scoped observability (ISSUE 4): trace-context propagation
with IDs, per-request SLO accounting (TTFT/TPOT/queue-wait/e2e +
quantile estimation + declarative SLO rules), the anomaly flight
recorder, and compile/HBM telemetry — including the chaos/latency
acceptance run driving LLMEngine with prefix caching + preemption +
an injected slow step."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.observability import flight, metrics, slo, tracing
from paddle_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _obs_clean():
    """Every test starts and ends disabled with empty series/ring, no
    SLO rules and a disarmed flight recorder (all process-global)."""
    obs.disable()
    obs.reset()
    slo.clear()
    flight.disarm()
    cap = tracing.capacity()
    yield
    obs.disable()
    obs.reset()
    slo.clear()
    flight.disarm()
    tracing.set_capacity(cap)
    faults.clear_all()


def _series(name):
    return obs.snapshot()[name]["series"]


# ---------------------------------------------------------------------------
# trace context: IDs, propagation, adoption, exports
# ---------------------------------------------------------------------------
class TestTraceContext:
    def test_nested_spans_share_trace_and_parent(self):
        obs.enable()
        with obs.span("outer") as so:
            assert obs.current_trace() == {"trace_id": so.trace_id,
                                           "span_id": so.span_id}
            with obs.span("inner") as si:
                pass
        assert obs.current_trace() is None
        inner, outer = tracing.events()
        assert inner["trace_id"] == outer["trace_id"] == so.trace_id
        assert inner["parent_id"] == outer["span_id"]
        assert "parent_id" not in outer
        assert inner["span_id"] == si.span_id != outer["span_id"]

    def test_sibling_top_level_spans_get_fresh_traces(self):
        obs.enable()
        with obs.span("a"):
            pass
        with obs.span("b"):
            pass
        a, b = tracing.events()
        assert a["trace_id"] != b["trace_id"]

    def test_trace_context_adoption(self):
        obs.enable()
        tid, root = tracing.new_trace_id(), tracing.new_span_id()
        with obs.trace_context(tid, root):
            with obs.span("child"):
                pass
        (ev,) = tracing.events()
        assert ev["trace_id"] == tid
        assert ev["parent_id"] == root

    def test_request_id_lands_in_args(self):
        obs.enable()
        with obs.span("s", request_id="req-7", extra=1):
            pass
        (ev,) = tracing.events()
        assert ev["args"] == {"request_id": "req-7", "extra": 1}

    def test_disabled_span_has_no_ids_and_no_context(self):
        s = obs.span("x", request_id="r")
        with s:
            assert obs.current_trace() is None
        assert s.trace_id is None and s.span_id is None
        assert tracing.events() == []

    @pytest.mark.obs
    def test_jsonl_export_carries_ids(self, tmp_path):
        obs.enable()
        with obs.span("a"):
            with obs.span("b"):
                pass
        path = obs.export_jsonl(str(tmp_path / "t.jsonl"))
        lines = [json.loads(l) for l in open(path)]
        assert all({"trace_id", "span_id"} <= set(e) for e in lines)

    def test_ingest_appends_foreign_events(self):
        obs.enable()
        foreign = [{"name": "w", "ph": "X", "pid": 99999, "tid": 1,
                    "ts": 1.0, "dur": 2.0, "trace_id": "aa",
                    "span_id": "bb"}]
        tracing.ingest(foreign)
        # Merged events are tagged so a co-resident FleetAgent never
        # re-ships them; the caller's dicts are left untouched.
        assert tracing.events() == [dict(foreign[0], ingested=True)]
        assert "ingested" not in foreign[0]


# ---------------------------------------------------------------------------
# quantile estimation + summary percentiles
# ---------------------------------------------------------------------------
class TestQuantiles:
    def test_histogram_quantile_interpolates(self):
        obs.enable()
        h = obs.registry().histogram("t_qtl_seconds", "h",
                                     buckets=(0.1, 0.2, 0.4))
        for v in (0.05, 0.15, 0.15, 0.3, 0.35, 0.5):
            h.observe(v)
        assert h.quantile(0.0) == pytest.approx(0.05)
        assert h.quantile(0.5) == pytest.approx(0.2)
        assert h.quantile(1.0) == pytest.approx(0.5)
        assert 0.2 < h.quantile(0.75) <= 0.4

    def test_quantile_empty_and_clamped(self):
        obs.enable()
        h = obs.registry().histogram("t_qtl2_seconds", "h",
                                     buckets=(1.0,))
        assert h.quantile(0.5) is None
        h.observe(5.0)              # lands in +Inf bucket
        # clamped to the observed max, not unbounded
        assert h.quantile(0.99) == pytest.approx(5.0)

    def test_fraction_le(self):
        bounds, counts = (0.1, 0.2), [2, 2, 1]     # +Inf overflow: 1
        assert metrics.fraction_le(bounds, counts, 0.1) == \
            pytest.approx(0.4)
        assert metrics.fraction_le(bounds, counts, 0.15) == \
            pytest.approx(0.6)     # half of the (0.1, 0.2] bucket
        # past the last bound, the +Inf bucket counts as exceeded
        # unless the observed max says otherwise
        assert metrics.fraction_le(bounds, counts, 99.0) == \
            pytest.approx(0.8)
        assert metrics.fraction_le(bounds, counts, 99.0, hi=5.0) == 1.0
        assert metrics.fraction_le(bounds, [0, 0, 0], 0.1) is None

    def test_summary_reports_percentiles(self):
        obs.enable()
        h = obs.registry().histogram("t_sum_seconds", "h",
                                     buckets=(0.1, 0.2))
        for v in (0.05, 0.15, 0.25):
            h.observe(v)
        entry = obs.summary()["histograms"]["t_sum_seconds"]
        assert {"p50", "p95", "count", "mean"} <= set(entry)
        assert entry["p50"] <= entry["p95"] <= entry["max"]


# ---------------------------------------------------------------------------
# the reset contract (satellite fix, pinned)
# ---------------------------------------------------------------------------
class TestResetContract:
    def test_reset_clears_metrics_and_trace_ring(self):
        """obs.reset() is the FULL observable-state reset: series AND
        ring together; trace_clear() stays the narrow ring-only
        call."""
        obs.enable()
        c = obs.registry().counter("t_rst_total", "h")
        c.inc(3)
        with obs.span("s"):
            pass
        assert tracing.events()
        obs.reset()
        assert _series("t_rst_total")[()] == 0
        assert tracing.events() == []
        # trace_clear: ring only — metrics keep their values
        c.inc(2)
        with obs.span("s2"):
            pass
        obs.trace_clear()
        assert tracing.events() == []
        assert _series("t_rst_total")[()] == 2


# ---------------------------------------------------------------------------
# SLO rules
# ---------------------------------------------------------------------------
class TestSLO:
    def _hist(self, name="t_slo_seconds"):
        h = obs.registry().histogram(name, "h", buckets=(0.1, 0.5))
        return h

    def test_evaluate_pass_and_breach(self):
        obs.enable()
        h = self._hist()
        for v in (0.05, 0.05, 0.05, 0.3):   # 75% under 0.1
            h.observe(v)
        slo.add(slo.SLO("loose", "t_slo_seconds", threshold_s=0.5,
                        objective=0.9))
        slo.add(slo.SLO("tight", "t_slo_seconds", threshold_s=0.1,
                        objective=0.9))
        res = {r.name: r for r in slo.evaluate()}
        assert res["loose"].ok and res["loose"].attained == 1.0
        assert not res["tight"].ok
        assert res["tight"].attained == pytest.approx(0.75)
        assert _series("paddle_tpu_slo_breaches_total")[("tight",)] == 1
        assert ("loose",) not in \
            _series("paddle_tpu_slo_breaches_total")

    def test_empty_metric_passes_vacuously(self):
        obs.enable()
        self._hist("t_slo2_seconds")
        slo.add(slo.SLO("empty", "t_slo2_seconds", threshold_s=0.1,
                        objective=0.99))
        (r,) = slo.evaluate()
        assert r.ok and r.attained is None and r.count == 0
        assert not r.missing        # registered, just no traffic yet

    def test_unknown_metric_flagged_missing(self):
        """A typo'd metric name must be DETECTABLE, not an eternal
        vacuous pass."""
        obs.enable()
        slo.add(slo.SLO("typo", "t_slo_nope_seconds", threshold_s=0.1,
                        objective=0.99))
        (r,) = slo.evaluate()
        assert r.ok and r.missing
        assert "MISSING-METRIC" in repr(r)

    def test_invalid_rules_rejected(self):
        with pytest.raises(ValueError, match="objective"):
            slo.SLO("x", "m", threshold_s=1.0, objective=1.5)
        with pytest.raises(ValueError, match="threshold"):
            slo.SLO("x", "m", threshold_s=0.0, objective=0.9)

    @pytest.mark.obs
    def test_breach_drops_flight_bundle(self, tmp_path):
        obs.enable()
        h = self._hist("t_slo3_seconds")
        h.observe(9.0)
        slo.add(slo.SLO("burnt", "t_slo3_seconds", threshold_s=0.1,
                        objective=0.5))
        flight.arm(str(tmp_path))
        slo.evaluate()
        (b,) = flight.bundles()
        assert "slo_breach" in os.path.basename(b)
        assert flight.load_bundle(b)["meta"]["detail"]["name"] == \
            "burnt"


# ---------------------------------------------------------------------------
# flight recorder mechanics
# ---------------------------------------------------------------------------
@pytest.mark.obs
class TestFlightRecorder:
    def test_bundle_contents_and_counter(self, tmp_path):
        obs.enable()
        obs.registry().counter("t_fl_total", "h").inc(4)
        with obs.span("engine.step"):
            pass
        flight.arm(str(tmp_path), retention=4)
        path = flight.trigger("manual", detail={"why": "test"})
        assert path and os.path.basename(path).endswith("_manual")
        b = flight.load_bundle(path)
        assert b["meta"]["reason"] == "manual"
        assert b["meta"]["detail"] == {"why": "test"}
        assert b["metrics"]["t_fl_total"]["series"][0]["value"] == 4
        assert any(e["name"] == "engine.step" for e in b["trace"])
        assert _series("paddle_tpu_flight_bundles_total")[
            ("manual",)] == 1

    def test_retention_and_cooldown(self, tmp_path):
        flight.arm(str(tmp_path), retention=2)
        for _ in range(5):
            flight.trigger("manual")
        assert len(flight.bundles()) == 2
        flight.disarm()
        flight.arm(str(tmp_path), retention=8, min_interval_s=3600.0)
        assert flight.trigger("manual") is not None
        assert flight.trigger("manual") is None     # inside cooldown

    def test_disarmed_is_inert(self, tmp_path):
        assert flight.trigger("manual") is None
        assert not flight.armed()

    def test_rearm_resumes_numbering_and_sweeps_tmp(self, tmp_path):
        """A postmortem tool restarts by definition: re-arming over a
        directory with bundles from a previous incarnation must not
        collide names (a collision makes the rename fail and silently
        drops the next dump), and half-written .tmp_ dirs from a crash
        mid-dump are swept."""
        flight.arm(str(tmp_path))
        first = flight.trigger("manual")
        first_seq = int(os.path.basename(first).split("_")[1])
        flight.disarm()
        import paddle_tpu.observability.flight as fl
        fl._SEQ = 0                       # simulate a fresh process
        os.makedirs(str(tmp_path / ".tmp_bundle_000009_manual"))
        flight.arm(str(tmp_path))
        p = flight.trigger("manual")
        assert p is not None
        assert int(os.path.basename(p).split("_")[1]) == first_seq + 1
        assert len(flight.bundles()) == 2
        assert not [n for n in os.listdir(str(tmp_path))
                    if n.startswith(".tmp_")]

    def test_fault_point_capture(self, tmp_path):
        flight.arm(str(tmp_path), capture_faults=True)
        with pytest.raises(RuntimeError):
            with faults.inject("engine.step", exc=RuntimeError("x"),
                               times=1):
                faults.fault_point("engine.step")
        (b,) = flight.bundles()
        assert "fault_point" in os.path.basename(b)
        assert flight.load_bundle(b)["meta"]["detail"]["fault"] == \
            "engine.step"
        flight.disarm()
        assert faults._ON_FIRE is None      # hook released


# ---------------------------------------------------------------------------
# check_metric_names: help-string enforcement (satellite)
# ---------------------------------------------------------------------------
class TestMetricNameAudit:
    def test_empty_help_rejected(self):
        import sys
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        sys.path.insert(0, os.path.join(root, "tools"))
        try:
            import check_metric_names as cmn
        finally:
            sys.path.pop(0)
        bad = [("counter", "paddle_tpu_bad_total", "", "x.py")]
        good = [("counter", "paddle_tpu_ok_total", "help", "x.py")]
        readme = "paddle_tpu_bad_total paddle_tpu_ok_total"
        probs = cmn.check(bad + good, readme)
        assert len(probs) == 1 and "help" in probs[0]


# ---------------------------------------------------------------------------
# engine: the chaos/latency acceptance run
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tiny_gpt():
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import gpt_tiny
    pt.seed(0)
    return GPTForCausalLM(gpt_tiny())


def _preempting_engine(model):
    """The test_prefix_cache preemption config tightened by one block
    (7 usable): two shared-prefix requests through a pool too small
    for both EVEN when the warm prefix cache shares their 2 prefix
    pages (2 shared + 3 + 3 unique > 7), so every pass — cold or warm
    — preempts and resumes through the prefix cache."""
    from paddle_tpu.inference import LLMEngine
    return LLMEngine(model, max_batch=2, block_size=8, num_blocks=8,
                     decode_chunk=4, prompt_quantum=16,
                     max_model_len=64, enable_prefix_caching=True)


def _prompts():
    rng = np.random.default_rng(3)
    prefix = rng.integers(0, 1024, (16,)).astype(np.int32)
    return [np.concatenate(
        [prefix, rng.integers(0, 1024, (t,)).astype(np.int32)])
        for t in (1, 2)]


def _run(eng, prompts, tag, n_new=20):
    for i, p in enumerate(prompts):
        eng.add_request(f"{tag}{i}", p, max_new_tokens=n_new)
    done = {}
    while eng.has_unfinished:
        for r in eng.step():
            done[r.request_id] = r
    return done


def _request_events(rid):
    return [e for e in tracing.events()
            if e.get("args", {}).get("request_id") == rid]


@pytest.mark.obs
class TestEngineRequestTracing:
    def test_chaos_latency_acceptance(self, tiny_gpt, tmp_path):
        """The ISSUE 4 acceptance scenario in one run: prefix caching +
        preemption, an injected slow step, connected per-request trace
        trees, TTFT/TPOT percentiles in summary(), exactly one flight
        bundle holding the triggering trace, and the compile counter
        agreeing with the engine's executable caches."""
        obs.enable()
        eng = _preempting_engine(tiny_gpt)
        prompts = _prompts()
        # two identical warmup passes compile EVERY executable the
        # measured pass needs (pass 2 admits through the prefix cache
        # seeded by pass 1, which uses its own resume executable), so
        # the armed pass is compile-free and only the injected delay
        # can trip the latency trigger
        _run(eng, prompts, "w")
        assert eng.stats["preemptions"] >= 1
        _run(eng, prompts, "x")
        obs.trace_clear()       # measured pass gets a clean ring
        pre_preempts = eng.stats["preemptions"]
        pre_hits = eng.stats["prefix_cache_hit_tokens"]

        flight.arm(str(tmp_path), step_latency_threshold_s=1.5)
        with faults.inject("engine.step", delay=2.0, times=1):
            done = _run(eng, prompts, "c")
        flight.disarm()

        assert sorted(done) == ["c0", "c1"]
        assert all(r.ok for r in done.values())
        # preemption + prefix-cache resume happened in the MEASURED run
        assert eng.stats["preemptions"] > pre_preempts
        assert eng.stats["prefix_cache_hit_tokens"] > pre_hits

        # -- every finished request: one CONNECTED single-trace tree --
        preempted = set()
        for rid in ("c0", "c1"):
            evs = _request_events(rid)
            names = [e["name"] for e in evs]
            assert "request" in names          # root span present
            assert "request.queue_wait" in names
            assert "request.prefill" in names
            (root,) = [e for e in evs if e["name"] == "request"]
            assert root["args"]["finish_reason"] == "length"
            assert "parent_id" not in root
            tids = {e["trace_id"] for e in evs}
            assert tids == {root["trace_id"]}  # ONE trace
            for e in evs:
                if e is not root:
                    assert e["parent_id"] == root["span_id"]
            if "request.preempt" in names:
                preempted.add(rid)
                # resumed lifecycle stays in the SAME trace: a second
                # admission (queue_wait) and a second prefill
                assert names.count("request.queue_wait") >= 2
                assert names.count("request.prefill") >= 2
        assert preempted                       # chaos actually bit

        # -- SLO accounting is live --
        sm = obs.summary()["histograms"]
        for name in ("paddle_tpu_request_ttft_seconds",
                     "paddle_tpu_request_tpot_seconds",
                     "paddle_tpu_request_queue_wait_seconds",
                     "paddle_tpu_request_e2e_seconds"):
            assert {"p50", "p95"} <= set(sm[name]), name
        fin = _series("paddle_tpu_request_finished_total")
        assert fin[("length",)] >= 6           # all three passes

        # -- exactly ONE flight bundle, holding the triggering trace --
        (bundle,) = flight.bundles(str(tmp_path))
        assert "step_latency" in os.path.basename(bundle)
        b = flight.load_bundle(bundle)
        assert b["meta"]["detail"]["step_seconds"] > 1.5
        slow = [e for e in b["trace"]
                if e.get("span_id") == b["meta"]["detail"]["span_id"]]
        assert len(slow) == 1 and slow[0]["name"] == "engine.step"
        assert slow[0]["dur"] >= 1.5e6         # µs
        # stats snapshot is AT trigger time (mid-run), not end state
        assert pre_preempts <= \
            b["meta"]["extra"]["engine_stats"]["preemptions"] <= \
            eng.stats["preemptions"]

        # -- compile telemetry agrees with the dispatch caches --
        comp = _series("paddle_tpu_compile_total")
        engine_compiles = sum(
            v for (fam, _out), v in comp.items()
            if fam.startswith("engine"))
        assert engine_compiles == len(eng._fns)
        # prefix caching + preemption means the pool-reading ragged
        # variant compiled (prefix-resume rides the ragged family now)
        assert sum(v for (fam, _out), v in comp.items()
                   if fam == "engine_ragged") >= 1
        ct = _series("paddle_tpu_compile_seconds")
        assert sum(v["count"] for v in ct.values()) == engine_compiles

        # -- HBM gauges sampled at the step boundary --
        hbm = _series("paddle_tpu_hbm_page_pool_bytes")
        assert hbm[("reserved",)] > 0
        assert 0 <= hbm[("used",)] <= hbm[("reserved",)]
        assert _series("paddle_tpu_hbm_live_array_bytes")[()] > 0

    def test_deadline_miss_drops_bundle(self, tiny_gpt, tmp_path):
        obs.enable()
        eng = _preempting_engine(tiny_gpt)
        flight.arm(str(tmp_path))
        eng.add_request("late", _prompts()[0], max_new_tokens=4,
                        deadline_s=0.0)        # expired on arrival
        (r,) = eng.step()
        assert r.finish_reason == "deadline"
        (b,) = flight.bundles(str(tmp_path))
        assert "deadline_miss" in os.path.basename(b)
        meta = flight.load_bundle(b)["meta"]
        assert meta["detail"]["request_id"] == "late"
        fin = _series("paddle_tpu_request_finished_total")
        assert fin[("deadline",)] == 1

    def test_disabled_mode_no_allocation_growth(self, tiny_gpt):
        """The acceptance overhead guard, extended over the NEW hot
        paths: request_id spans, the flight-armed check, and the
        request histograms — all one flag check when off."""
        import tracemalloc
        h = obs.registry().histogram("t_ov2_seconds", "h")
        c = obs.registry().counter("t_ov2_total", "h")
        assert not obs.enabled() and not flight.armed()
        for _ in range(16):
            with obs.span("t.ov2", request_id="r"):
                pass
            h.observe(0.1)
            c.inc()
            if flight._ARMED:
                pytest.fail("armed")
        tracemalloc.start()
        base = tracemalloc.get_traced_memory()[0]
        for _ in range(5000):
            with obs.span("t.ov2", request_id="r"):
                pass
            h.observe(0.1)
            c.inc()
            if flight._ARMED:
                pytest.fail("armed")
        grown = tracemalloc.get_traced_memory()[0] - base
        tracemalloc.stop()
        assert grown < 2048, f"disabled-mode ops leaked {grown}B"
        assert tracing.events() == []


# ---------------------------------------------------------------------------
# worker-side spans survive the spawn boundary
# ---------------------------------------------------------------------------
class SpawnTraceDs(Dataset):
    """Module-level (spawn-picklable) tiny dataset."""

    def __init__(self, n=12):
        self.n = n

    def __getitem__(self, i):
        return np.full((4,), i, np.float32)

    def __len__(self):
        return self.n


class TestSpawnBoundaryTraces:
    def test_worker_spans_merge_into_parent_ring(self):
        obs.enable()
        out = list(DataLoader(SpawnTraceDs(n=12), batch_size=4,
                              num_workers=2))
        assert len(out) == 3
        worker_evs = [e for e in tracing.events()
                      if e["name"] == "io.worker.batch"]
        assert len(worker_evs) == 3
        # recorded IN the spawned processes, not re-stamped here
        assert all(e["pid"] != os.getpid() for e in worker_evs)
        assert {e["args"]["bi"] for e in worker_evs} == {0, 1, 2}
        # and the metric snapshot still merges alongside (PR 2 path)
        assert _series(
            "paddle_tpu_dataloader_worker_batches_total")[()] == 3


# ---------------------------------------------------------------------------
# compile-family budget: the ragged rewire's executable-zoo contract
# ---------------------------------------------------------------------------
@pytest.mark.obs
class TestCompileFamilyBudget:
    """ISSUE 7 acceptance: the old (bucket, pages)-keyed prefill /
    prefix-resume / verify executable zoo collapsed into ONE
    `engine_ragged` family (bucketed only on total-token count) plus
    the retained `engine_decode` chunk family. A mixed workload pins
    (a) the counter == the executable-cache size, (b) the closed
    family label set, and (c) the executable count under a budget —
    so any zoo regrowth (a new family, per-(kind, length) keys
    sneaking back) fails tier-1."""

    BUDGET = 10     # ragged total-token buckets (x with/without pool)
                    # + pow2 decode chunks for THIS workload; the old
                    # zoo keyed the same traffic by (bucket, pages,
                    # kind) and grew per dimension

    def test_mixed_workload_stays_inside_family_budget(self, tiny_gpt):
        from paddle_tpu.inference import LLMEngine, SpeculativeConfig
        obs.enable()
        rng = np.random.default_rng(7)
        eng = LLMEngine(tiny_gpt, max_batch=2, block_size=8,
                        num_blocks=24, decode_chunk=4,
                        prompt_quantum=16, max_model_len=64,
                        enable_prefix_caching=True,
                        speculative_config=SpeculativeConfig(
                            proposer="ngram",
                            num_speculative_tokens=4))
        # mixed traffic: two repetitive prompts first (they share the
        # batch, so the n-gram proposer drafts and verify rows run),
        # then shared-prefix prompts of assorted lengths (fresh
        # prefill + prefix-resume rows), plus the chunked decode every
        # sequence runs between launches
        rep = [np.tile(rng.integers(0, 1024, (8,)).astype(np.int32), 4)
               for _ in range(2)]
        prefix = rng.integers(0, 1024, (8,)).astype(np.int32)
        prompts = rep + [np.concatenate(
            [prefix, rng.integers(0, 1024, (t,)).astype(np.int32)])
            for t in (1, 5, 9)]
        done = _run(eng, prompts, "mix", n_new=16)
        assert len(done) == len(prompts)
        assert all(r.ok for r in done.values())
        assert eng.stats["ragged_launches"] > 0
        assert eng.stats["spec_steps"] > 0      # verify rode ragged
        assert eng.stats["decode_chunks"] > 0   # chunk family retained
        comp = _series("paddle_tpu_compile_total")
        # zero-valued rows are label sets other tests registered before
        # obs.reset() (reset zeroes values but keeps series) — only
        # families that actually compiled THIS workload count
        fams = {fam for (fam, _out), v in comp.items() if v}
        # the whole point: TWO engine families, nothing else
        assert fams <= {"engine_ragged", "engine_decode"}, fams
        assert "engine_ragged" in fams
        engine_compiles = sum(v for (fam, _out), v in comp.items()
                              if fam.startswith("engine"))
        # counter == executable cache (no recompiles, no untimed fns)
        assert engine_compiles == len(eng._fns), (
            engine_compiles, sorted(eng._fns))
        assert engine_compiles <= self.BUDGET, (
            f"executable zoo regrew: {engine_compiles} > "
            f"{self.BUDGET}: {sorted(eng._fns)}")
        ct = _series("paddle_tpu_compile_seconds")
        assert sum(v["count"] for (fam,), v in ct.items()
                   if fam.startswith("engine")) == engine_compiles
        # cost-model telemetry rides the same families: one expected-
        # flops gauge row per live family, no orphan families (a gauge
        # family that never compiled would be a telemetry path the
        # budget above cannot see)
        fl = _series("paddle_tpu_executable_flops")
        fl_fams = {fam for (fam,), v in fl.items() if v}
        assert fl_fams == fams, (fl_fams, fams)
        by = _series("paddle_tpu_executable_bytes")
        for fam in fams:
            assert by[(fam, "accessed")] > 0
            for kind in ("output", "temp", "argument"):
                assert (fam, kind) in by
        assert {fam for (fam, _k), v in by.items() if v} == fams
