"""Chaos suite for the resilience layer (paddle_tpu.resilience).

Every fault here is injected deterministically through the named fault
points in paddle_tpu.resilience.faults — no sleeping-and-hoping. The
contracts under test:

  * LLMEngine: a poisoned/OOMing/deadline-expired request fails ALONE;
    every other admitted request finishes with oracle-exact tokens and
    its pages return to the pool.
  * DataLoader: a worker SIGKILL'd (hard-exited) mid-epoch is detected
    and respawned; the epoch completes identically to serial, and no
    /dev/shm segment outlives the loader on ANY exit path.
  * Checkpoints: a crash at any point between shard writes and the
    final rename leaves the previous checkpoint untouched;
    resume_latest() restores the newest COMPLETE checkpoint, skipping
    torn/corrupted ones.
"""
import os
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear_all()
    yield
    faults.clear_all()


# ---------------------------------------------------------------------------
# the harness itself
# ---------------------------------------------------------------------------
class TestFaultHarness:
    def test_disarmed_is_noop(self):
        faults.fault_point("nothing.armed", x=1)   # must not raise

    def test_context_scoping_and_fired(self):
        with faults.inject("chaos.a", exc=ValueError("boom")):
            with pytest.raises(ValueError, match="boom"):
                faults.fault_point("chaos.a")
        faults.fault_point("chaos.a")              # cleared on exit
        assert faults.fired("chaos.a") == 1

    def test_times_budget(self):
        faults.inject("chaos.b", exc=RuntimeError, times=2)
        for _ in range(2):
            with pytest.raises(RuntimeError):
                faults.fault_point("chaos.b")
        faults.fault_point("chaos.b")              # budget exhausted
        assert faults.fired("chaos.b") == 2

    def test_match_and_when(self):
        with faults.inject("chaos.c", exc=KeyError, match={"rid": "bad"}):
            faults.fault_point("chaos.c", rid="good")
            with pytest.raises(KeyError):
                faults.fault_point("chaos.c", rid="bad")
        with faults.inject("chaos.d", exc=KeyError,
                           when=lambda ctx: ctx.get("i", 0) > 3):
            faults.fault_point("chaos.d", i=1)
            with pytest.raises(KeyError):
                faults.fault_point("chaos.d", i=7)

    def test_delay(self):
        import time
        with faults.inject("chaos.e", delay=0.05):
            t0 = time.monotonic()
            faults.fault_point("chaos.e")
            assert time.monotonic() - t0 >= 0.05

    def test_when_may_call_back_into_faults(self):
        # sequencing predicate: fire B only after A has fired
        faults.inject("chaos.seq.a", exc=ValueError, times=1)
        faults.inject("chaos.seq.b", exc=RuntimeError,
                      when=lambda ctx: faults.fired("chaos.seq.a") > 0)
        faults.fault_point("chaos.seq.b")          # A not fired yet
        with pytest.raises(ValueError):
            faults.fault_point("chaos.seq.a")
        with pytest.raises(RuntimeError):
            faults.fault_point("chaos.seq.b")

    def test_snapshot_drops_when(self):
        faults.inject("chaos.f", exc=ValueError, match={"bi": 1})
        faults.inject("chaos.g", exc=ValueError, when=lambda c: True)
        names = {s.name for s in faults.snapshot()}
        assert names == {"chaos.f"}    # `when` callables don't pickle


# ---------------------------------------------------------------------------
# engine hardening
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tiny_gpt():
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import gpt_tiny
    pt.seed(0)
    return GPTForCausalLM(gpt_tiny())


def _engine(model, **kw):
    from paddle_tpu.inference import LLMEngine
    args = dict(max_batch=2, block_size=16, decode_chunk=4,
                prompt_quantum=16, max_model_len=64)
    args.update(kw)
    return LLMEngine(model, **args)


def _oracle(model, prompt, n_new):
    from paddle_tpu.models.generation import generate
    out = generate(model, pt.to_tensor(np.asarray(prompt, np.int32)[None]),
                   max_new_tokens=n_new).numpy()[0]
    return out[len(prompt):]


def _drain(eng):
    done = {}
    while eng.has_unfinished:
        for r in eng.step():
            done[r.request_id] = r
    return done


class TestEngineResilience:
    def test_tight_pool_no_decode_oom(self, tiny_gpt):
        """Regression (ADVICE r5 medium): decode leases are capped at
        the sequence's remaining token budget, so a pool sized exactly
        to add_request's feasibility check (need + trash page) serves
        the request instead of raising MemoryError mid-serving."""
        rng = np.random.default_rng(2)
        prompt = rng.integers(0, 1024, (17,)).astype(np.int32)
        # total 37 tokens -> need ceil(37/8)=5 blocks; pool = 5 + trash
        eng = _engine(tiny_gpt, max_batch=1, block_size=8, num_blocks=6,
                      decode_chunk=4)
        (r,) = eng.generate([prompt], max_new_tokens=20)
        assert r.ok and len(r.output_ids) == 20
        np.testing.assert_array_equal(r.output_ids,
                                      _oracle(tiny_gpt, prompt, 20))
        assert eng.cache.available_blocks == 5

    def test_poisoned_decode_isolated(self, tiny_gpt):
        """Injected OOM at decode: the poisoned request is failed and
        evicted, every other admitted request finishes exactly."""
        rng = np.random.default_rng(5)
        prompts = {k: rng.integers(0, 1024, (9,)).astype(np.int32)
                   for k in ("good1", "bad", "good2")}
        eng = _engine(tiny_gpt)
        for k, p in prompts.items():
            eng.add_request(k, p, max_new_tokens=8)
        with faults.inject("engine.decode.seq",
                           exc=MemoryError("chaos decode OOM"),
                           match={"rid": "bad"}):
            done = _drain(eng)
        assert done["bad"].finish_reason == "error"
        assert "chaos decode OOM" in done["bad"].error
        for k in ("good1", "good2"):
            assert done[k].ok
            np.testing.assert_array_equal(
                done[k].output_ids, _oracle(tiny_gpt, prompts[k], 8))
        # the failed request's pages went back to the pool
        assert eng.cache.available_blocks == \
            eng.cache.allocator.num_blocks - 1
        assert eng.stats["failed_requests"] == 1

    def test_poisoned_prefill_isolated(self, tiny_gpt):
        rng = np.random.default_rng(6)
        pg = rng.integers(0, 1024, (9,)).astype(np.int32)
        pb = rng.integers(0, 1024, (11,)).astype(np.int32)
        eng = _engine(tiny_gpt)
        eng.add_request("good", pg, max_new_tokens=6)
        eng.add_request("bad", pb, max_new_tokens=6)
        with faults.inject("engine.prefill.seq",
                           exc=RuntimeError("chaos prefill"),
                           match={"rid": "bad"}):
            done = _drain(eng)
        assert done["bad"].finish_reason == "error"
        assert done["good"].ok
        np.testing.assert_array_equal(done["good"].output_ids,
                                      _oracle(tiny_gpt, pg, 6))
        assert eng.cache.available_blocks == \
            eng.cache.allocator.num_blocks - 1

    def test_deadline_evicted_while_neighbor_finishes(self, tiny_gpt):
        rng = np.random.default_rng(7)
        pv = rng.integers(0, 1024, (9,)).astype(np.int32)
        pn = rng.integers(0, 1024, (12,)).astype(np.int32)
        eng = _engine(tiny_gpt)
        clock = {"now": 0.0}
        eng._now = lambda: clock["now"]     # deterministic TTL clock
        eng.add_request("victim", pv, max_new_tokens=30, deadline_s=5.0)
        eng.add_request("neighbor", pn, max_new_tokens=8)
        eng.step()                          # both admitted, decoding
        assert any(s is not None and s.rid == "victim"
                   for s in eng.slots)
        clock["now"] = 10.0                 # victim's TTL elapses
        done = _drain(eng)
        assert done["victim"].finish_reason == "deadline"
        assert not done["victim"].ok
        assert done["neighbor"].ok
        np.testing.assert_array_equal(done["neighbor"].output_ids,
                                      _oracle(tiny_gpt, pn, 8))
        assert eng.cache.available_blocks == \
            eng.cache.allocator.num_blocks - 1
        assert eng.stats["deadline_expired"] == 1

    def test_load_shedding_rejects_with_reason(self, tiny_gpt):
        eng = _engine(tiny_gpt, max_batch=1, block_size=8, num_blocks=5,
                      shed_load=True, max_waiting=1)
        eng.add_request("big", np.zeros(20, np.int32), max_new_tokens=20)
        eng.add_request("long", np.zeros(60, np.int32), max_new_tokens=10)
        eng.add_request("ok1", np.zeros(4, np.int32), max_new_tokens=2)
        eng.add_request("spill", np.zeros(4, np.int32), max_new_tokens=2)
        done = _drain(eng)
        assert done["big"].finish_reason == "rejected"
        assert "cache blocks" in done["big"].error
        assert done["long"].finish_reason == "rejected"
        assert "max_model_len" in done["long"].error
        assert done["spill"].finish_reason == "rejected"
        assert "queue is full" in done["spill"].error
        assert done["ok1"].ok
        assert eng.stats["rejected_requests"] == 3

    def test_legacy_raise_admission_preserved(self, tiny_gpt):
        eng = _engine(tiny_gpt, max_batch=1, block_size=8, num_blocks=5)
        with pytest.raises(MemoryError):
            eng.add_request("big", np.zeros(20, np.int32),
                            max_new_tokens=20)
        with pytest.raises(ValueError):
            eng.add_request("long", np.zeros(60, np.int32),
                            max_new_tokens=10)


# ---------------------------------------------------------------------------
# crash-safe checkpoints
# ---------------------------------------------------------------------------
class TestCrashSafeCheckpoint:
    def _save(self, path, arr):
        from paddle_tpu import distributed as dist
        dist.checkpoint.save_state_dict(
            {"w": pt.to_tensor(arr)}, str(path))

    def test_crash_between_tmp_and_rename(self, tmp_path):
        from paddle_tpu import distributed as dist
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        self._save(tmp_path / "step_10", a)
        with pytest.raises(KeyboardInterrupt):
            with faults.inject("checkpoint.before_rename",
                               exc=KeyboardInterrupt("crash")):
                self._save(tmp_path / "step_20", a * 2)
        # the destination never appeared; only hidden staging litter
        assert not (tmp_path / "step_20").exists()
        with pytest.raises(KeyboardInterrupt):
            with faults.inject("checkpoint.before_meta",
                               exc=KeyboardInterrupt("crash")):
                self._save(tmp_path / "step_30", a * 3)
        assert not (tmp_path / "step_30").exists()
        dst = {"w": pt.to_tensor(np.zeros_like(a))}
        got = dist.checkpoint.resume_latest(dst, str(tmp_path),
                                            cleanup=True)
        assert got and got.endswith("step_10")
        np.testing.assert_array_equal(dst["w"].numpy(), a)
        # cleanup reaped the staging dirs
        assert not [f for f in os.listdir(tmp_path) if ".tmp-" in f]

    def test_resume_skips_torn_checkpoint(self, tmp_path):
        from paddle_tpu import distributed as dist
        a = np.arange(8, dtype=np.float32)
        self._save(tmp_path / "step_1", a)
        self._save(tmp_path / "step_2", a * 2)
        # corrupt the newest checkpoint's shard payload
        step2 = tmp_path / "step_2"
        shard = next(f for f in os.listdir(step2) if f.endswith(".npy"))
        (step2 / shard).write_bytes(b"garbage")
        assert dist.checkpoint.verify_checkpoint(str(step2))
        assert dist.checkpoint.is_complete(str(tmp_path / "step_1"))
        dst = {"w": pt.to_tensor(np.zeros_like(a))}
        with pytest.warns(UserWarning, match="torn checkpoint"):
            got = dist.checkpoint.resume_latest(dst, str(tmp_path))
        assert got.endswith("step_1")
        np.testing.assert_array_equal(dst["w"].numpy(), a)

    def test_soft_failure_between_overwrite_renames_rolls_back(
            self, tmp_path):
        """Overwriting save raises after the previous checkpoint moved
        aside but before the new one landed: the previous checkpoint is
        rolled back in place — plain load_state_dict(path) keeps
        working, no resume needed."""
        from paddle_tpu import distributed as dist
        a = np.arange(8, dtype=np.float32)
        self._save(tmp_path / "latest", a)
        with pytest.raises(KeyboardInterrupt):
            with faults.inject("checkpoint.between_renames",
                               exc=KeyboardInterrupt("crash")):
                self._save(tmp_path / "latest", a * 2)
        dst = {"w": pt.to_tensor(np.zeros_like(a))}
        dist.checkpoint.load_state_dict(dst, str(tmp_path / "latest"))
        np.testing.assert_array_equal(dst["w"].numpy(), a)  # v1, not v2

    def test_hard_crash_between_overwrite_renames_repaired(
            self, tmp_path):
        """HARD crash (no rollback ran) in the same window: the
        previous COMPLETE checkpoint is stranded as a hidden .old dir
        with the destination absent — resume_latest restores it."""
        from paddle_tpu import distributed as dist
        a = np.arange(8, dtype=np.float32)
        self._save(tmp_path / "latest", a)
        # simulate the post-SIGKILL state the rollback can't reach
        os.replace(tmp_path / "latest", tmp_path / ".latest.old-999")
        dst = {"w": pt.to_tensor(np.zeros_like(a))}
        got = dist.checkpoint.resume_latest(dst, str(tmp_path),
                                            cleanup=True)
        assert got and got.endswith("latest")
        np.testing.assert_array_equal(dst["w"].numpy(), a)
        assert not [f for f in os.listdir(tmp_path)
                    if ".tmp-" in f or ".old-" in f]

    def test_resume_latest_empty_root(self, tmp_path):
        from paddle_tpu import distributed as dist
        assert dist.checkpoint.resume_latest({}, str(tmp_path)) is None
        assert dist.checkpoint.resume_latest(
            {}, str(tmp_path / "missing")) is None

    def test_resume_ignores_non_checkpoint_dirs(self, tmp_path):
        """Sibling dirs without a metadata.json (logs/, tensorboard/)
        are not checkpoints: never warned about, never quarantined —
        even with cleanup=True."""
        from paddle_tpu import distributed as dist
        a = np.arange(4, dtype=np.float32)
        self._save(tmp_path / "step_3", a)
        (tmp_path / "logs").mkdir()
        (tmp_path / "logs" / "events.txt").write_text("hi")
        dst = {"w": pt.to_tensor(np.zeros_like(a))}
        with warnings.catch_warnings():
            warnings.simplefilter("error")      # any warning fails
            got = dist.checkpoint.resume_latest(dst, str(tmp_path),
                                                cleanup=True)
        assert got.endswith("step_3")
        assert (tmp_path / "logs" / "events.txt").read_text() == "hi"

    def test_manifest_written_and_filtered(self, tmp_path):
        from paddle_tpu import distributed as dist
        self._save(tmp_path / "c", np.ones(4, np.float32))
        files = dist.checkpoint.get_checkpoint_files(str(tmp_path / "c"))
        assert files == ["w"]
        assert dist.checkpoint.verify_checkpoint(
            str(tmp_path / "c")) == []

    def test_framework_io_atomic_save(self, tmp_path):
        fp = str(tmp_path / "model.pdparams")
        a = np.arange(6, dtype=np.float32)
        pt.save({"a": pt.to_tensor(a)}, fp)
        with pytest.raises(KeyboardInterrupt):
            with faults.inject("framework_io.before_rename",
                               exc=KeyboardInterrupt("crash")):
                pt.save({"a": pt.to_tensor(a * 9)}, fp)
        # crash mid-save: the previous pickle is intact, not torn
        np.testing.assert_array_equal(pt.load(fp)["a"].numpy(), a)


# ---------------------------------------------------------------------------
# self-healing DataLoader
# ---------------------------------------------------------------------------
class ShmDs(Dataset):
    """Module-level (spawn-picklable); big samples force the
    SharedMemory transport path."""

    def __init__(self, n=24):
        self.n = n

    def __getitem__(self, i):
        rng = np.random.RandomState(i)
        return rng.standard_normal(64 * 1024).astype(np.float32), \
            np.int64(i)

    def __len__(self):
        return self.n


class EnvGuardDs(ShmDs):
    """Asserts the spawn-env contract: JAX_PLATFORMS=cpu must already
    be set when the dataset is UNPICKLED in the worker (i.e. the env
    guard runs before any user code), not just when __getitem__ runs."""

    def __setstate__(self, state):
        assert os.environ.get("JAX_PLATFORMS") == "cpu", \
            "dataset unpickled before the worker's env guard"
        self.__dict__.update(state)


def tensor_collate(batch):
    """Module-level (itself spawn-picklable) collate returning framework
    Tensors — Tensor.__reduce__ (numpy roundtrip) makes the OUTPUT
    spawn-picklable, so the loader keeps the process tier."""
    xs, ys = zip(*batch)
    return (pt.to_tensor(np.stack(xs)), pt.to_tensor(np.asarray(ys)))


def _shm_segments():
    try:
        return {f for f in os.listdir("/dev/shm")}
    except FileNotFoundError:       # macOS etc. — skip the accounting
        return None


def _collect(loader):
    return [(np.asarray(x.numpy()), np.asarray(y.numpy()))
            for x, y in loader]


class TestSelfHealingDataLoader:
    def test_worker_killed_mid_epoch_heals(self):
        # the FATAL healing contract: hard-exit (SIGKILL-equivalent:
        # no error report, no cleanup) worker 0 the first time it
        # reaches batch 2. The respawn batch NUMBER is load-dependent
        # — the hard exit can kill the queue's feeder thread before
        # batch 0's pickle ever reaches the pipe, in which case the
        # parent (correctly) respawns at batch 0 — so only the respawn
        # itself is asserted; the real contract is the batch-exact
        # healed epoch checked below. The /dev/shm accounting lives in
        # its own (flaky-listed) test so THIS correctness contract can
        # never ride out a timing race un-asserted.
        ds = ShmDs(n=24)
        serial = _collect(DataLoader(ds, batch_size=4, num_workers=0))
        with faults.inject("io.worker.batch", exit_code=1, times=1,
                           match={"bi": 2, "attempt": 0}):
            with pytest.warns(UserWarning,
                              match="respawning at batch"):
                healed = _collect(DataLoader(ds, batch_size=4,
                                             num_workers=2))
        assert len(healed) == len(serial) == 6
        for (sx, sy), (px, py) in zip(serial, healed):
            np.testing.assert_array_equal(sx, px)
            np.testing.assert_array_equal(sy, py)

    def test_worker_kill_shm_leak_accounting(self):
        # the shm-leak accounting for the same kill scenario, split
        # out (ISSUE 13) so its timing race never exempts the healing
        # contract above: _process_worker documents a real residual
        # window (a hard kill landing strictly between segment
        # creation in _pack and the payload reaching the parent's
        # queue loses that batch's segment names with the dead
        # worker), so one attempt can legitimately leak a segment —
        # best-of-2, and the test is on tools/known_failures.json's
        # "flaky" list (reported, not fatal) because the race loses
        # both attempts under load on the shared box. A SYSTEMATIC
        # leak still fails both attempts everywhere else.
        ds = ShmDs(n=24)
        leaked = None
        for _attempt in range(2):
            before = _shm_segments()
            with faults.inject("io.worker.batch", exit_code=1, times=1,
                               match={"bi": 2, "attempt": 0}):
                with pytest.warns(UserWarning,
                                  match="respawning at batch"):
                    healed = _collect(DataLoader(ds, batch_size=4,
                                                 num_workers=2))
            assert len(healed) == 6
            leaked = None if before is None \
                else _shm_segments() - before
            if not leaked:
                break
        assert not leaked, f"leaked /dev/shm segments twice: {leaked}"

    def test_restart_budget_exhausts(self):
        ds = ShmDs(n=24)
        # kill EVERY incarnation at batch 2 -> bounded restarts, then a
        # clear error (not a hang)
        with faults.inject("io.worker.batch", exit_code=1,
                           match={"bi": 2}):
            with pytest.raises(RuntimeError, match="exhausted"), \
                    warnings.catch_warnings():
                warnings.simplefilter("ignore")
                _collect(DataLoader(ds, batch_size=4, num_workers=2,
                                    max_worker_restarts=1))

    def test_early_exit_unlinks_all_segments(self):
        ds = ShmDs(n=64)
        before = _shm_segments()
        loader = DataLoader(ds, batch_size=4, num_workers=2,
                            prefetch_factor=2)
        it = iter(loader)
        next(it)
        next(it)
        it.close()      # generator finally: stop -> join -> drain
        if before is not None:
            import time
            time.sleep(0.2)
            assert _shm_segments() <= before, \
                "early consumer exit leaked /dev/shm segments"
        # the loader is reusable afterwards
        assert len(_collect(loader)) == 16

    def test_env_guard_precedes_unpickle(self, monkeypatch):
        # parent without JAX_PLATFORMS: the child can only pass
        # EnvGuardDs.__setstate__ if worker_main's guard ran first
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        out = _collect(DataLoader(EnvGuardDs(n=8), batch_size=4,
                                  num_workers=2))
        assert len(out) == 2

    def test_tensor_collate_stays_on_process_tier(self):
        """Tensor-returning collate_fns used to demote to the thread
        tier (Tensors had no pickle protocol); Tensor.__reduce__ lifted
        that — the probe must accept them, spawn real workers, and the
        batches must round-trip the worker->parent queue exactly."""
        ds = ShmDs(n=8)
        loader = DataLoader(ds, batch_size=4, num_workers=2,
                            collate_fn=tensor_collate)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            out = _collect(loader)
        assert not [w for w in caught
                    if "falling back" in str(w.message)], \
            "Tensor collate demoted to the thread tier"
        assert loader._spawn_picklable_result is True
        assert len(out) == 2
        serial = _collect(DataLoader(ds, batch_size=4, num_workers=0,
                                     collate_fn=tensor_collate))
        for (sx, sy), (px, py) in zip(serial, out):
            np.testing.assert_array_equal(sx, px)
            np.testing.assert_array_equal(sy, py)


# ---------------------------------------------------------------------------
# fused optimizer: instance-hyper mutation honored (satellite)
# ---------------------------------------------------------------------------
def test_fused_step_honors_hyper_mutation():
    from paddle_tpu.optimizer import Adam

    def run(fused):
        os.environ["PADDLE_TPU_FUSED_OPT"] = "1" if fused else "0"
        try:
            pt.seed(0)
            lin = pt.nn.Linear(8, 8)
            x = pt.to_tensor(np.random.default_rng(0).standard_normal(
                (4, 8)).astype(np.float32))
            opt = Adam(learning_rate=0.01, parameters=lin.parameters())
            for i in range(6):
                if i == 3:      # mid-training mutation
                    opt.beta1 = 0.5
                    opt.epsilon = 1e-3
                loss = (lin(x) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
            return [np.asarray(p._data) for p in lin.parameters()], opt
        finally:
            os.environ.pop("PADDLE_TPU_FUSED_OPT", None)

    fused, opt = run(True)
    eager, _ = run(False)
    for a, b in zip(fused, eager):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=5e-6)
    # the mutation recompiled (2 signatures) instead of being ignored
    assert len(opt.__dict__["_fused_step_cache"]) == 2
