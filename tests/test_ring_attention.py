"""Ring attention (sequence-parallel long context, SURVEY §5.7)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import paddle_tpu as pt
from paddle_tpu.distributed.meta_parallel import ring_flash_attention
from paddle_tpu.distributed.meta_parallel.ring_attention import (
    ring_attention_impl)


def _mesh(n=8, axis="sep"):
    return Mesh(np.array(jax.devices()[:n]), (axis,))


def _dense_ref(q, k, v, causal):
    s = np.einsum("bqhd,bkhd->bhqk", q.astype(np.float64),
                  k.astype(np.float64)) / np.sqrt(q.shape[-1])
    if causal:
        qpos = np.arange(s.shape[-2])[:, None]
        kpos = np.arange(s.shape[-1])[None, :]
        s = np.where(qpos >= kpos, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    o = np.einsum("bhqk,bkhd->bhqd", p, v.astype(np.float64))
    return np.swapaxes(o, 1, 2)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_dense(causal):
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 64, 2, 16
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, h, d)).astype(np.float32)
    v = rng.standard_normal((b, s, h, d)).astype(np.float32)
    out = ring_flash_attention(q, k, v, _mesh(), causal=causal)
    ref = _dense_ref(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                               atol=2e-4)


@pytest.mark.slow  # >25s on the 1-core CI box; --runslow tier
def test_gradients_match_dense(causal=True):
    rng = np.random.default_rng(1)
    b, s, h, d = 1, 32, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    mesh = _mesh()

    def ring_loss(q, k, v):
        return (ring_attention_impl(q, k, v, mesh, causal=True)
                .astype(jnp.float32) ** 2).sum()

    def dense_loss(q, k, v):
        sc = 1.0 / np.sqrt(d)
        s_ = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sc
        qpos = jnp.arange(s_.shape[-2])[:, None]
        kpos = jnp.arange(s_.shape[-1])[None, :]
        s_ = jnp.where(qpos >= kpos, s_, -1e30)
        p = jax.nn.softmax(s_, -1)
        o = jnp.einsum("bhqk,bkhd->bhqd", p, v)
        return (jnp.swapaxes(o, 1, 2) ** 2).sum()

    gr = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-3, atol=1e-4)


def test_output_stays_sequence_sharded():
    rng = np.random.default_rng(2)
    q = rng.standard_normal((1, 64, 2, 8)).astype(np.float32)
    mesh = _mesh()
    out = ring_flash_attention(q, q, q, mesh, causal=True)
    spec = out._data.sharding.spec
    assert "sep" in str(spec), spec


def test_tensor_api_and_uneven_raises():
    rng = np.random.default_rng(3)
    x = pt.to_tensor(rng.standard_normal((1, 64, 2, 8))
                     .astype(np.float32))
    out = ring_flash_attention(x, x, x, _mesh(), causal=True)
    assert out.shape == [1, 64, 2, 8]
    bad = pt.to_tensor(rng.standard_normal((1, 60, 2, 8))
                       .astype(np.float32))
    with pytest.raises(ValueError, match="not divisible"):
        ring_flash_attention(bad, bad, bad, _mesh())


@pytest.mark.slow  # >25s on the 1-core CI box; --runslow tier
def test_eager_tape_backward():
    # code-review r2: eager Tensor path must record on the tape
    rng = np.random.default_rng(4)
    x = pt.to_tensor(rng.standard_normal((1, 32, 2, 8))
                     .astype(np.float32), stop_gradient=False)
    out = ring_flash_attention(x, x, x, _mesh(), causal=True)
    assert not out.stop_gradient
    (out ** 2).sum().backward()
    assert x.grad is not None
    assert np.count_nonzero(x.grad.numpy()) > 0
