"""Direct conformance tests for the RNN scan-body ops (VERDICT r3 weak
#3: lstm_scan/gru_scan/simple_rnn_scan were only exercised indirectly
via the RNN layer tests). Oracle: torch.nn.{LSTM,GRU,RNN} single layer —
the gate orders match the reference's (paddle == torch here)."""
import numpy as np
import pytest
import torch

import paddle_tpu as pt
from paddle_tpu.nn.layers.rnn import _gru_scan, _lstm_scan, _rnn_scan

S, B, I, H = 7, 3, 5, 4


def _w(shape, seed):
    return np.random.default_rng(seed).standard_normal(shape).astype(
        np.float32) * 0.3


def _torch_rnn(mode, x, h0, c0, w_ih, w_hh, b_ih, b_hh):
    m = {"LSTM": torch.nn.LSTM, "GRU": torch.nn.GRU,
         "RNN": torch.nn.RNN}[mode](I, H, 1, batch_first=False)
    with torch.no_grad():
        m.weight_ih_l0.copy_(torch.from_numpy(w_ih))
        m.weight_hh_l0.copy_(torch.from_numpy(w_hh))
        m.bias_ih_l0.copy_(torch.from_numpy(b_ih))
        m.bias_hh_l0.copy_(torch.from_numpy(b_hh))
    tx = torch.from_numpy(x)
    th0 = torch.from_numpy(h0[None])
    if mode == "LSTM":
        out, (hT, cT) = m(tx, (th0, torch.from_numpy(c0[None])))
        return out.detach().numpy(), hT[0].detach().numpy(), \
            cT[0].detach().numpy()
    out, hT = m(tx, th0)
    return out.detach().numpy(), hT[0].detach().numpy()


@pytest.fixture
def x_h():
    return _w((S, B, I), 0), _w((B, H), 1)


def test_lstm_scan_matches_torch(x_h):
    x, h0 = x_h
    c0 = _w((B, H), 2)
    w_ih, w_hh = _w((4 * H, I), 3), _w((4 * H, H), 4)
    b_ih, b_hh = _w((4 * H,), 5), _w((4 * H,), 6)
    out, hT, cT = _lstm_scan(pt.to_tensor(x), pt.to_tensor(h0),
                             pt.to_tensor(c0), pt.to_tensor(w_ih),
                             pt.to_tensor(w_hh), pt.to_tensor(b_ih),
                             pt.to_tensor(b_hh))
    wout, whT, wcT = _torch_rnn("LSTM", x, h0, c0, w_ih, w_hh, b_ih, b_hh)
    np.testing.assert_allclose(out.numpy(), wout, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(hT.numpy(), whT, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(cT.numpy(), wcT, rtol=1e-4, atol=1e-5)


def test_gru_scan_matches_torch(x_h):
    x, h0 = x_h
    w_ih, w_hh = _w((3 * H, I), 3), _w((3 * H, H), 4)
    b_ih, b_hh = _w((3 * H,), 5), _w((3 * H,), 6)
    out, hT = _gru_scan(pt.to_tensor(x), pt.to_tensor(h0),
                        pt.to_tensor(w_ih), pt.to_tensor(w_hh),
                        pt.to_tensor(b_ih), pt.to_tensor(b_hh))
    wout, whT = _torch_rnn("GRU", x, h0, None, w_ih, w_hh, b_ih, b_hh)
    np.testing.assert_allclose(out.numpy(), wout, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(hT.numpy(), whT, rtol=1e-4, atol=1e-5)


def test_simple_rnn_scan_matches_torch(x_h):
    x, h0 = x_h
    w_ih, w_hh = _w((H, I), 3), _w((H, H), 4)
    b_ih, b_hh = _w((H,), 5), _w((H,), 6)
    out, hT = _rnn_scan(pt.to_tensor(x), pt.to_tensor(h0),
                        pt.to_tensor(w_ih), pt.to_tensor(w_hh),
                        pt.to_tensor(b_ih), pt.to_tensor(b_hh))
    wout, whT = _torch_rnn("RNN", x, h0, None, w_ih, w_hh, b_ih, b_hh)
    np.testing.assert_allclose(out.numpy(), wout, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(hT.numpy(), whT, rtol=1e-4, atol=1e-5)


def test_reverse_scan_is_time_flip(x_h):
    """reverse=True must equal flip(forward(flip(x))) for every body."""
    x, h0 = x_h
    w_ih, w_hh = _w((H, I), 3), _w((H, H), 4)
    b_ih, b_hh = _w((H,), 5), _w((H,), 6)
    rev, hT_r = _rnn_scan(pt.to_tensor(x), pt.to_tensor(h0),
                          pt.to_tensor(w_ih), pt.to_tensor(w_hh),
                          pt.to_tensor(b_ih), pt.to_tensor(b_hh),
                          reverse=True)
    fwd, hT_f = _rnn_scan(pt.to_tensor(x[::-1].copy()),
                          pt.to_tensor(h0), pt.to_tensor(w_ih),
                          pt.to_tensor(w_hh), pt.to_tensor(b_ih),
                          pt.to_tensor(b_hh))
    np.testing.assert_allclose(rev.numpy(), fwd.numpy()[::-1],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(hT_r.numpy(), hT_f.numpy(), rtol=1e-5)


def test_scan_bodies_differentiable():
    """The scan ops must record on the tape (they train inside nn.LSTM)."""
    x = pt.to_tensor(_w((S, B, I), 0))
    h0 = pt.to_tensor(np.zeros((B, H), np.float32))
    c0 = pt.to_tensor(np.zeros((B, H), np.float32))
    w_ih = pt.to_tensor(_w((4 * H, I), 3))
    w_ih.stop_gradient = False
    w_hh = pt.to_tensor(_w((4 * H, H), 4))
    out, hT, cT = _lstm_scan(x, h0, c0, w_ih, w_hh, None, None)
    (out * out).mean().backward()
    g = w_ih.grad
    assert g is not None and np.isfinite(g.numpy()).all()
    assert np.abs(g.numpy()).sum() > 0
