"""Replicated serving with failover (inference/router.py): a
health-checked Router over N in-process LLMEngine replicas,
chaos-tested.

Oracle: a single never-killed LLMEngine (itself oracle-pinned against
models.generation.generate in test_llm_engine). Greedy decoding is
deterministic, so every accepted request must finish with bit-identical
output no matter how many replicas died under it — failover re-serves
from the original prompt, the strict allocator proves zero pages leak
on survivors, and the failover/reroute counters must match the
injected kill count exactly."""
import json
import os
import sys
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.inference import (LLMEngine, ReplicaGone, Router)
from paddle_tpu.models import GPTForCausalLM
from paddle_tpu.models.gpt import gpt_tiny
from paddle_tpu.observability import tracing
from paddle_tpu.resilience import faults


@pytest.fixture(scope="module")
def tiny_gpt():
    pt.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.fixture(autouse=True)
def _clean():
    faults.clear_all()
    obs.disable()
    obs.reset()
    yield
    faults.clear_all()
    obs.disable()
    obs.reset()


def _factory(model):
    """Same engine shapes as test_llm_engine so the persistent XLA
    cache is warm. Each call builds an INDEPENDENT engine (own pool,
    own executable cache) sharing the read-only weights."""
    def make(_i):
        return LLMEngine(model, max_batch=2, block_size=16,
                         decode_chunk=4, prompt_quantum=16,
                         max_model_len=64)
    return make


def _prompts(n=4, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 1024, (k,)).astype(np.int32)
            for k in (5, 9, 13, 21)[:n]]


def _assert_no_leaks(router):
    """Every surviving replica's pool fully reconciles: free + parked
    (LRU) pages == all blocks but the leased trash page."""
    for h in router.replicas:
        if h.engine is None:
            continue
        cache = h.engine.cache
        assert cache.available_blocks == \
            cache.allocator.num_blocks - 1, h.name


def _serve(router, prompts, n_new, rid_prefix=""):
    for i, p in enumerate(prompts):
        router.submit(f"{rid_prefix}{i}", p, max_new_tokens=n_new)
    done = {}
    while router.has_unfinished:
        for r in router.step():
            done[r.request_id] = r
    return done


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------
class TestRouting:
    def test_matches_single_engine(self, tiny_gpt):
        prompts = _prompts()
        router = Router(_factory(tiny_gpt), n_replicas=2)
        done = _serve(router, prompts, 8)
        single = _factory(tiny_gpt)(0).generate(prompts,
                                                max_new_tokens=8)
        for i, s in enumerate(single):
            r = done[f"{i}"]
            assert r.ok
            np.testing.assert_array_equal(r.output_ids, s.output_ids)
        # both replicas actually served (least-loaded distribution)
        assert all(h.engine.stats["prefills"] > 0
                   for h in router.replicas)
        _assert_no_leaks(router)

    def test_affinity_routes_to_prefix_holder(self, tiny_gpt):
        rng = np.random.default_rng(7)
        prefix = rng.integers(0, 1024, (32,)).astype(np.int32)
        turn = [np.concatenate([prefix, rng.integers(
            0, 1024, (k,)).astype(np.int32)]) for k in (3, 5, 7)]
        router = Router(_factory(tiny_gpt), n_replicas=2)
        router.submit("t0", turn[0], max_new_tokens=4, session_id="s")
        owner = router._owner["t0"].name
        while router.has_unfinished:
            router.step()
        # later turns share the 32-token (2-page) prefix: the peek
        # finds it parked on the owner and routes there
        for j, p in enumerate(turn[1:], 1):
            router.submit(f"t{j}", p, max_new_tokens=4,
                          session_id="s")
            assert router._owner[f"t{j}"].name == owner
            while router.has_unfinished:
                router.step()
        assert router.stats["affinity_hit_tokens"] >= 64
        eng = next(h.engine for h in router.replicas
                   if h.name == owner)
        assert eng.stats["prefix_cache_hit_tokens"] >= 64

    def test_affinity_off_is_least_loaded(self, tiny_gpt):
        rng = np.random.default_rng(8)
        prefix = rng.integers(0, 1024, (32,)).astype(np.int32)
        prompts = [np.concatenate([prefix, rng.integers(
            0, 1024, (k,)).astype(np.int32)]) for k in (3, 5)]
        router = Router(_factory(tiny_gpt), n_replicas=2,
                        affinity=False)
        for i, p in enumerate(prompts):
            router.submit(i, p, max_new_tokens=4)
        owners = {router._owner[i].name for i in range(2)}
        assert len(owners) == 2         # blind spread, no clustering
        assert router.stats["affinity_hit_tokens"] == 0
        while router.has_unfinished:
            router.step()

    def test_affinity_headroom_spreads_load(self, tiny_gpt):
        """Affinity must not concentrate a hot prefix onto one replica
        past the headroom factor: once the cached replica's inflight
        blows `affinity_max_inflight_factor` x the least-loaded's, the
        pick falls back to least-loaded (the PR 19 traffic-harness
        gotcha — session affinity erases fleet pipelining)."""
        rng = np.random.default_rng(9)
        prefix = rng.integers(0, 1024, (32,)).astype(np.int32)
        turns = [np.concatenate([prefix, rng.integers(
            0, 1024, (k,)).astype(np.int32)]) for k in (3, 5, 7, 9)]
        router = Router(_factory(tiny_gpt), n_replicas=2,
                        affinity_max_inflight_factor=1.0)
        # seed the prefix on one replica, drained to idle
        router.submit("seed", turns[0], max_new_tokens=4)
        owner = router._owner["seed"].name
        while router.has_unfinished:
            router.step()
        # pile up same-prefix admissions WITHOUT stepping: affinity
        # wants the owner every time, but at factor 1.0 the owner may
        # never carry more inflight than the idle replica + 1 — the
        # overflow spreads
        for j, p in enumerate(turns):
            router.submit(f"q{j}", p, max_new_tokens=4)
        owners = [router._owner[f"q{j}"].name
                  for j in range(len(turns))]
        assert owners.count(owner) == 2
        assert len(set(owners)) == 2        # both replicas carry load
        while router.has_unfinished:
            router.step()
        _assert_no_leaks(router)

    def test_affinity_headroom_none_always_honors_cache(self,
                                                        tiny_gpt):
        """factor=None pins the old behavior: affinity wins no matter
        how lopsided the load gets."""
        rng = np.random.default_rng(9)
        prefix = rng.integers(0, 1024, (32,)).astype(np.int32)
        turns = [np.concatenate([prefix, rng.integers(
            0, 1024, (k,)).astype(np.int32)]) for k in (3, 5, 7, 9)]
        router = Router(_factory(tiny_gpt), n_replicas=2,
                        affinity_max_inflight_factor=None)
        router.submit("seed", turns[0], max_new_tokens=4)
        owner = router._owner["seed"].name
        while router.has_unfinished:
            router.step()
        for j, p in enumerate(turns):
            router.submit(f"q{j}", p, max_new_tokens=4)
        owners = {router._owner[f"q{j}"].name
                  for j in range(len(turns))}
        assert owners == {owner}        # all piled onto the holder
        while router.has_unfinished:
            router.step()
        _assert_no_leaks(router)

    def test_duplicate_rid_refused(self, tiny_gpt):
        router = Router(_factory(tiny_gpt), n_replicas=2)
        router.submit("a", _prompts(1)[0], max_new_tokens=4)
        with pytest.raises(ValueError):
            router.submit("a", _prompts(1)[0], max_new_tokens=4)
        while router.has_unfinished:
            router.step()


# ---------------------------------------------------------------------------
# chaos: kill a replica mid-stream, three ways
# ---------------------------------------------------------------------------
class TestChaosFailover:
    def _chaos_run(self, model, spec_kw, router_kw=None,
                   warm=False):
        """Start 4 requests on 2 replicas, step once so everything is
        mid-stream, kill replica-0 via the named fault point, run to
        completion. Returns (router, {rid: result})."""
        prompts = _prompts()
        router = Router(_factory(model), n_replicas=2,
                        **(router_kw or {}))
        if warm:                # compile every bucket first
            for r in _serve(router, prompts, 12, "w").values():
                assert r.ok
        for i, p in enumerate(prompts):
            router.submit(i, p, max_new_tokens=12)
        router.step()           # prefills done, decodes in flight
        victims = len(router.replicas.handles[0].inflight)
        assert victims > 0      # the kill really is mid-stream
        done = {}
        with faults.inject("router.replica.step",
                           match={"replica": "replica-0"}, times=1,
                           **spec_kw):
            while router.has_unfinished:
                for r in router.step():
                    done[r.request_id] = r
        return router, done, victims

    def _assert_bit_identical(self, model, done):
        single = _factory(model)(0).generate(_prompts(),
                                             max_new_tokens=12)
        for i, s in enumerate(single):
            assert done[i].ok, (i, done[i].finish_reason,
                                done[i].error)
            np.testing.assert_array_equal(done[i].output_ids,
                                          s.output_ids)

    def test_exception_kill(self, tiny_gpt):
        obs.enable()
        router, done, victims = self._chaos_run(
            tiny_gpt, dict(exc=RuntimeError("chaos: step blew up")))
        self._assert_bit_identical(tiny_gpt, done)
        _assert_no_leaks(router)
        assert router.stats["failovers"] == 1       # == injected kills
        assert router.stats["reroutes"] == victims
        # zero-valued rows are label sets other tests registered
        # before obs.reset() (reset zeroes values but keeps series)
        assert {k: v for k, v in _series(
            "paddle_tpu_router_failovers_total").items() if v} == \
            {("exception",): 1}
        rr = sum(_series("paddle_tpu_router_reroutes_total").values())
        assert rr == victims

    def test_hard_exit_kill(self, tiny_gpt):
        """ReplicaGone — the in-process stand-in for a hard process
        exit: the engine object is discarded unasked (no cleanup ran),
        and reintegration must build a FRESH engine."""
        router, done, victims = self._chaos_run(
            tiny_gpt, dict(exc=ReplicaGone("chaos: SIGKILL")),
            router_kw=dict(cooldown_s=3600.0))
        self._assert_bit_identical(tiny_gpt, done)
        _assert_no_leaks(router)
        h0 = router.replicas.handles[0]
        assert h0.state == "dead" and h0.engine is None
        assert router.stats["failovers"] == 1
        assert router.stats["reroutes"] == victims

    def test_hang_past_timeout(self, tiny_gpt):
        """A step that completes but blows unhealthy_step_s: the
        replica is quarantined ALIVE — in-flight requests drain
        through abort_request (pages reclaimed on the spot) and the
        warm engine is kept for reintegration."""
        router, done, victims = self._chaos_run(
            tiny_gpt, dict(delay=1.5),
            router_kw=dict(unhealthy_step_s=1.0, cooldown_s=3600.0),
            warm=True)
        for k in list(done):        # drop the warmup requests
            if isinstance(k, str) and k.startswith("w"):
                del done[k]
        self._assert_bit_identical(tiny_gpt, done)
        h0 = router.replicas.handles[0]
        assert h0.state == "dead" and h0.engine is not None
        assert h0.engine.stats["aborted_requests"] == victims
        assert router.stats["failovers"] == 1
        assert router.stats["reroutes"] == victims
        _assert_no_leaks(router)    # incl. the drained quarantined one

    def test_no_cross_request_poisoning(self, tiny_gpt):
        """A poisoned REQUEST is not a poisoned REPLICA: the engine's
        per-sequence isolation fails it alone, the router keeps the
        replica, and every neighbor (same replica included) stays
        oracle-exact."""
        prompts = _prompts()
        router = Router(_factory(tiny_gpt), n_replicas=2)
        for i, p in enumerate(prompts):
            router.submit(i, p, max_new_tokens=8)
        bad = 0
        victim_replica = router._owner[bad].name
        with faults.inject("engine.decode.seq",
                           exc=RuntimeError("poison"),
                           match={"rid": bad}):
            done = {}
            while router.has_unfinished:
                for r in router.step():
                    done[r.request_id] = r
        assert done[bad].finish_reason == "error"
        assert router.stats["failovers"] == 0
        assert all(h.live for h in router.replicas)
        single = _factory(tiny_gpt)(0).generate(prompts,
                                                max_new_tokens=8)
        for i, s in enumerate(single):
            if i == bad:
                continue
            np.testing.assert_array_equal(done[i].output_ids,
                                          s.output_ids)
        assert router._owner == {}
        _assert_no_leaks(router)
        assert victim_replica   # (documented: the replica survived)

    def test_trace_tree_stays_connected(self, tiny_gpt):
        """Failover keeps ONE trace per request: the re-served
        attempt's engine events and the router.reroute marker all
        carry the original trace_id, and the terminal root span is
        anchored at the ORIGINAL enqueue."""
        obs.enable()
        prompts = _prompts()
        router = Router(_factory(tiny_gpt), n_replicas=2)
        for i, p in enumerate(prompts):
            router.submit(i, p, max_new_tokens=12)
        router.step()
        victims = [r.rid for r in
                   router.replicas.handles[0].inflight.values()]
        with faults.inject("router.replica.step",
                           exc=ReplicaGone("chaos"),
                           match={"replica": "replica-0"}, times=1):
            while router.has_unfinished:
                router.step()
        evs = tracing.events()
        rid = victims[0]
        roots = [e for e in evs if e["name"] == "request"
                 and e.get("args", {}).get("request_id") == str(rid)]
        assert len(roots) == 1          # ONE terminal root span
        tid = roots[0]["trace_id"]
        reroutes = [e for e in evs if e["name"] == "router.reroute"
                    and e.get("args", {}).get("request_id") == str(rid)]
        assert reroutes and all(e["trace_id"] == tid
                                for e in reroutes)
        prefills = [e for e in evs if e["name"] == "request.prefill"
                    and e.get("args", {}).get("request_id") == str(rid)]
        # prefilled on the doomed replica AND re-prefilled on the
        # survivor — same tree
        assert len(prefills) >= 2
        assert all(e["trace_id"] == tid for e in prefills)


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------
class TestCircuitBreaker:
    def test_backoff_doubles_and_reintegrates_fresh(self, tiny_gpt):
        router = Router(_factory(tiny_gpt), n_replicas=2,
                        cooldown_s=10.0, cooldown_factor=2.0,
                        max_cooldown_s=25.0, probation_steps=1)
        clock = [1000.0]
        router._now = lambda: clock[0]
        h0 = router.replicas.handles[0]
        old_engine = h0.engine

        def kill_and_drain(n_new, tag):
            for i, p in enumerate(_prompts(2)):
                router.submit(f"{tag}{i}", p, max_new_tokens=n_new)
            with faults.inject("router.replica.step",
                               exc=ReplicaGone("chaos"),
                               match={"replica": "replica-0"},
                               times=1):
                while router.has_unfinished:
                    router.step()

        kill_and_drain(4, "a")
        assert h0.state == "dead" and h0.cooldown_s == 10.0
        # breaker open: new traffic routes around the dead replica
        router.submit("solo", _prompts(1)[0], max_new_tokens=4)
        assert router._owner["solo"].name == "replica-1"
        while router.has_unfinished:
            router.step()
        # cooldown elapses -> probation with a FRESH engine
        clock[0] += 10.5
        router.step()
        assert h0.state == "probation"
        assert h0.engine is not None and h0.engine is not old_engine
        # failure during probation re-trips at DOUBLED backoff
        kill_and_drain(4, "b")
        assert h0.state == "dead" and h0.cooldown_s == 20.0
        clock[0] += 20.5
        router.step()
        # a third trip is bounded by max_cooldown_s
        kill_and_drain(4, "c")
        assert h0.cooldown_s == 25.0
        clock[0] += 25.5
        router.step()                   # reintegrate -> probation
        assert h0.state == "probation"
        # clean probation step(s) restore healthy and RESET backoff
        done = _serve(router, _prompts(2, seed=99), 4, "d")
        assert all(r.ok for r in done.values())
        assert h0.state == "healthy" and h0.cooldown_s == 0.0
        assert router.stats["failovers"] == 3

    def test_idle_probation_burns_down(self, tiny_gpt):
        """A reintegrated replica that gets no traffic still finishes
        probation (it cannot fail while idle) — otherwise an unrelated
        failure hours later reads as a consecutive trip and doubles
        the backoff."""
        router = Router(_factory(tiny_gpt), n_replicas=2,
                        cooldown_s=5.0, probation_steps=2)
        clock = [0.0]
        router._now = lambda: clock[0]
        h0 = router.replicas.handles[0]
        for i, p in enumerate(_prompts(2)):
            router.submit(i, p, max_new_tokens=4)
        with faults.inject("router.replica.step",
                           exc=ReplicaGone("chaos"),
                           match={"replica": "replica-0"}, times=1):
            while router.has_unfinished:
                router.step()
        assert h0.state == "dead"
        clock[0] += 5.5
        router.step()                   # reintegrates; observe-only
        assert h0.state == "probation"
        router.step()                   # idle pass 1
        router.step()                   # idle pass 2 -> healthy
        assert h0.state == "healthy" and h0.cooldown_s == 0.0

    def test_shedding_when_capacity_drops(self, tiny_gpt):
        """Losing a replica halves capacity: the router degrades by
        shedding new admissions (finish_reason="rejected", reason on
        .error) instead of queue-collapsing onto the survivor —
        everything it DID accept still finishes."""
        router = Router(_factory(tiny_gpt), n_replicas=2,
                        max_inflight=2, cooldown_s=3600.0)
        prompts = _prompts()
        done = {}

        def pump(n=1):
            for _ in range(n):
                for r in router.step():
                    done[r.request_id] = r

        for i, p in enumerate(prompts):
            router.submit(i, p, max_new_tokens=8)     # 4 <= 2*2: all in
        pump()
        with faults.inject("router.replica.step",
                           exc=ReplicaGone("chaos"),
                           match={"replica": "replica-0"}, times=1):
            pump()
        assert len(router.replicas.live()) == 1
        # the survivor's cap is now 2: anything beyond it sheds
        # instead of queueing
        for j in range(3):
            router.submit(f"x{j}", prompts[0], max_new_tokens=8)
        while router.has_unfinished:
            pump()
        shed = [r for r in done.values()
                if r.finish_reason == "rejected"]
        assert shed and all("capacity" in r.error for r in shed)
        single = _factory(tiny_gpt)(0).generate(prompts,
                                                max_new_tokens=8)
        for i, s in enumerate(single):      # accepted ones finished
            np.testing.assert_array_equal(done[i].output_ids,
                                          s.output_ids)
        _assert_no_leaks(router)


# ---------------------------------------------------------------------------
# engine abort hook (the drain primitive the router builds on)
# ---------------------------------------------------------------------------
class TestAbortRequest:
    def test_abort_mid_decode_frees_everything(self, tiny_gpt):
        eng = _factory(tiny_gpt)(0)
        prompts = _prompts(2)
        for i, p in enumerate(prompts):
            eng.add_request(i, p, max_new_tokens=16)
        eng.step()                      # both mid-decode
        assert eng.abort_request(0)
        (r,) = [r for r in eng.step() if r.request_id == 0]
        assert r.finish_reason == "aborted" and not r.ok
        assert len(r.output_ids) >= 1   # kept what it had
        assert eng.stats["aborted_requests"] == 1
        # neighbor unaffected, oracle-exact
        done = {}
        while eng.has_unfinished:
            for rr in eng.step():
                done[rr.request_id] = rr
        single = _factory(tiny_gpt)(0).generate(prompts,
                                                max_new_tokens=16)
        np.testing.assert_array_equal(done[1].output_ids,
                                      single[1].output_ids)
        # strict allocator: every page back in circulation (shareable
        # prefix blocks parked, the rest freed)
        assert eng.cache.available_blocks == \
            eng.cache.allocator.num_blocks - 1

    def test_abort_queued_before_prefill(self, tiny_gpt):
        eng = _factory(tiny_gpt)(0)
        free0 = eng.cache.allocator.num_free
        eng.add_request("q", _prompts(1)[0], max_new_tokens=8)
        assert eng.abort_request("q")
        assert eng.cache.allocator.num_free == free0    # never leased
        (r,) = eng.step()
        assert r.finish_reason == "aborted"
        assert len(r.output_ids) == 0
        assert not eng.has_unfinished

    def test_abort_unknown_rid(self, tiny_gpt):
        eng = _factory(tiny_gpt)(0)
        assert eng.abort_request("ghost") is False

    def test_abort_racing_failover_never_resurrects(self, tiny_gpt):
        """router.abort() then the replica dies before the aborted
        result surfaced: the cancellation must win — failover must NOT
        re-serve the request and hand the caller a completed result."""
        router = Router(_factory(tiny_gpt), n_replicas=2,
                        cooldown_s=3600.0)
        for i, p in enumerate(_prompts(2)):
            router.submit(i, p, max_new_tokens=16)
        router.step()
        h = router._owner[0]
        assert router.abort(0)
        with faults.inject("router.replica.step",
                           exc=ReplicaGone("chaos"),
                           match={"replica": h.name}, times=1):
            done = {}
            while router.has_unfinished:
                for r in router.step():
                    done[r.request_id] = r
        assert done[0].finish_reason == "aborted"
        assert router.stats["reroutes"] <= 1    # never request 0
        assert done[1].ok
        _assert_no_leaks(router)

    def test_infeasible_request_sheds(self, tiny_gpt):
        """An over-model-len request can fit NO replica: the engine's
        admission raises and the router converts it to a shed."""
        obs.enable()
        router = Router(_factory(tiny_gpt), n_replicas=2)
        router.submit("big", np.zeros(100, np.int32),
                      max_new_tokens=10)
        (r,) = router.step()
        assert r.finish_reason == "rejected"
        assert "infeasible" in r.error
        assert _series("paddle_tpu_router_shed_total")[
            ("infeasible",)] == 1
        assert not router.has_unfinished

    def test_router_abort_delivers_result(self, tiny_gpt):
        router = Router(_factory(tiny_gpt), n_replicas=2)
        for i, p in enumerate(_prompts(2)):
            router.submit(i, p, max_new_tokens=16)
        router.step()
        assert router.abort(0)
        done = {}
        while router.has_unfinished:
            for r in router.step():
                done[r.request_id] = r
        assert done[0].finish_reason == "aborted"
        assert done[1].ok
        _assert_no_leaks(router)


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------
def _series(name):
    return obs.snapshot()[name]["series"]


class TestRouterObservability:
    def test_replica_gauges_and_shed_counter(self, tiny_gpt):
        obs.enable()
        router = Router(_factory(tiny_gpt), n_replicas=2,
                        max_inflight=1, cooldown_s=3600.0)
        prompts = _prompts()
        router.submit(0, prompts[0], max_new_tokens=4)
        router.submit(1, prompts[1], max_new_tokens=4)
        router.submit(2, prompts[2], max_new_tokens=4)  # over cap
        done = {}
        while router.has_unfinished:
            for r in router.step():
                done[r.request_id] = r
        assert done[2].finish_reason == "rejected"
        shed = _series("paddle_tpu_router_shed_total")
        assert shed[("capacity",)] == 1
        state = _series("paddle_tpu_router_replica_state")
        assert state[("replica-0", "healthy")] == 1.0
        assert state[("replica-0", "dead")] == 0.0
        infl = _series("paddle_tpu_router_replica_inflight")
        assert infl[("replica-0",)] == 0.0
        fin = _series("paddle_tpu_request_finished_total")
        assert fin[("rejected",)] == 1
        assert fin[("length",)] == 2

    def test_disabled_mode_no_allocation_growth(self, tiny_gpt):
        """The standing acceptance guard, extended over the router's
        hot observability paths: gauge updates and idle scheduling
        passes are a flag check when obs is off."""
        import tracemalloc
        router = Router(_factory(tiny_gpt), n_replicas=2)
        assert not obs.enabled()
        def burst(n):
            for _ in range(n):
                router._update_gauges()
                router.step()
        # the interpreter retains a constant ~2KB of per-call-path
        # caches regardless of iteration count, so the guard compares
        # two windows of the SAME call site: a real per-op allocation
        # scales with n and shows up as the difference, the constant
        # residual cancels
        tracemalloc.start()
        burst(64)
        grown = []
        for n in (1000, 4000):
            base = tracemalloc.get_traced_memory()[0]
            burst(n)
            grown.append(tracemalloc.get_traced_memory()[0] - base)
        tracemalloc.stop()
        assert grown[1] - grown[0] < 2048, \
            f"disabled-mode router ops allocate per step: {grown}"
        assert tracing.events() == []


# ---------------------------------------------------------------------------
# obs_top replicas panel
# ---------------------------------------------------------------------------
class TestObsTopReplicasPanel:
    def _obs_top(self):
        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        sys.path.insert(0, tools)
        try:
            import obs_top
        finally:
            sys.path.remove(tools)
        return obs_top

    def test_renders_states_and_totals(self, tiny_gpt):
        obs_top = self._obs_top()
        obs.enable()
        router = Router(_factory(tiny_gpt), n_replicas=2,
                        cooldown_s=3600.0)
        for i, p in enumerate(_prompts(2)):
            router.submit(i, p, max_new_tokens=16)
        router.step()
        assert router.replicas.handles[1].inflight  # kill is mid-stream
        with faults.inject("router.replica.step",
                           exc=ReplicaGone("chaos"),
                           match={"replica": "replica-1"}, times=1):
            while router.has_unfinished:
                router.step()
        frame = obs_top.render(json.loads(obs.to_json()))
        assert "== replicas ==" in frame
        assert "replica-0" in frame and "healthy" in frame
        assert "replica-1" in frame and "dead" in frame
        assert "failovers=1" in frame
        line = [ln for ln in frame.splitlines()
                if "reroutes=" in ln][0]
        assert "shed" not in line or "shed:" in frame


# ---------------------------------------------------------------------------
# tools/known_failures.py — machine-checkable "no NEW failures"
# ---------------------------------------------------------------------------
class TestKnownFailures:
    def _tool(self):
        from tools import known_failures
        return known_failures

    def test_clean_log_passes(self, tmp_path):
        kf = self._tool()
        log = tmp_path / "t1.log"
        log.write_text("....\n10 passed in 1.0s\n")
        report = kf.check_log(str(log))
        assert report.new == [] and report.ok

    def test_known_failures_tolerated_new_flagged(self, tmp_path):
        kf = self._tool()
        known = kf.load_manifest()["failures"][0]
        log = tmp_path / "t1.log"
        log.write_text(
            f"FAILED {known} - AttributeError: shard_map\n"
            "FAILED tests/test_new.py::test_regression - boom\n"
            f"FAILED {known} - AttributeError: shard_map\n"
            "2 failed, 1 passed in 2.0s\n")
        report = kf.check_log(str(log))
        assert report.new == ["tests/test_new.py::test_regression"]
        assert not report.ok
        assert known in report.known_seen

    def test_flaky_failures_reported_not_fatal(self, tmp_path):
        kf = self._tool()
        flaky = kf.load_manifest()["flaky"][0]
        log = tmp_path / "t1.log"
        log.write_text(f"FAILED {flaky} - timing\n1 failed\n")
        report = kf.check_log(str(log))
        assert report.ok and report.flaky_seen == [flaky]

    def test_manifest_matches_checked_in_baseline(self):
        """The manifest is the machine-readable copy of the
        environment-failure list the repo docs cite — pin its shape
        so a drive-by edit can't silently blank the gate."""
        m = self._tool().load_manifest()
        assert len(m["failures"]) == 27
        assert all("::" in n for n in m["failures"] + m["flaky"])
