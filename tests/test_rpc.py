"""paddle.distributed.rpc conformance: in-process single-worker RPC and a
real two-process group over the master rendezvous (ref API:
python/paddle/distributed/rpc/rpc.py; test style: test/rpc/)."""
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _double(x):
    return 2 * x


def _boom():
    return 1 // 0


def test_single_worker_rpc_roundtrip():
    from paddle_tpu.distributed import rpc
    port = _free_port()
    rpc.init_rpc("solo", rank=0, world_size=1,
                 master_endpoint=f"127.0.0.1:{port}")
    try:
        assert rpc.rpc_sync("solo", _double, args=(21,)) == 42
        fut = rpc.rpc_async("solo", _double, args=(5,))
        assert fut.wait(timeout=30) == 10
        info = rpc.get_worker_info("solo")
        assert info.name == "solo" and info.rank == 0
        assert rpc.get_current_worker_info() == info
        assert [w.name for w in rpc.get_all_worker_infos()] == ["solo"]
        # remote exceptions propagate
        with pytest.raises(ZeroDivisionError):
            rpc.rpc_sync("solo", _boom)
    finally:
        rpc.shutdown()


WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    from paddle_tpu.distributed import rpc

    def mul(a, b):
        return a * b

    rank = int(sys.argv[1])
    rpc.init_rpc(f"worker{{rank}}".format(rank=rank), rank=rank,
                 world_size=2, master_endpoint=sys.argv[2])
    if rank == 0:
        out = rpc.rpc_sync("worker1", mul, args=(6, 7))
        assert out == 42, out
        futs = [rpc.rpc_async("worker1", mul, args=(i, i)) for i in range(4)]
        assert [f.wait() for f in futs] == [0, 1, 4, 9]
        print("RPC_OK")
    rpc.shutdown()
""")


class TestRpcObservability:
    """RPC reports itself: client/server latency + request counters,
    trace-context stitching across the call frame, and counted (never
    silent) frame rejections."""

    @pytest.fixture(autouse=True)
    def _clean(self):
        from paddle_tpu import observability as obs
        obs.disable()
        obs.reset()
        yield
        obs.disable()
        obs.reset()

    def test_serve_and_call_endpoint_no_rendezvous(self):
        from paddle_tpu.distributed import rpc
        srv, endpoint = rpc.serve()
        try:
            assert rpc.call_endpoint(endpoint, _double,
                                     args=(21,)) == 42
            with pytest.raises(ZeroDivisionError):
                rpc.call_endpoint(endpoint, _boom)
        finally:
            srv.shutdown()
            srv.server_close()

    def test_client_server_spans_share_trace(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.distributed import rpc
        from paddle_tpu.observability import tracing
        obs.enable()
        srv, endpoint = rpc.serve()
        try:
            with tracing.span("t.rpc_root"):
                assert rpc.call_endpoint(endpoint, _double,
                                         args=(4,)) == 8
        finally:
            srv.shutdown()
            srv.server_close()
        evs = tracing.events()
        client = [e for e in evs if e["name"] == "rpc.client"]
        server = [e for e in evs if e["name"] == "rpc.server"]
        root = [e for e in evs if e["name"] == "t.rpc_root"]
        assert len(client) == 1 and len(server) == 1
        # one connected tree: root -> rpc.client -> rpc.server
        assert client[0]["trace_id"] == root[0]["trace_id"]
        assert server[0]["trace_id"] == client[0]["trace_id"]
        assert server[0]["parent_id"] == client[0]["span_id"]
        assert client[0]["parent_id"] == root[0]["span_id"]
        assert client[0]["args"]["fn"] == "_double"

    def test_async_call_joins_callers_trace(self):
        """rpc_async runs on an executor thread; the caller's
        contextvars snapshot must ride along or the async client span
        starts a fresh, disconnected trace."""
        from paddle_tpu import observability as obs
        from paddle_tpu.distributed import rpc
        from paddle_tpu.observability import tracing
        obs.enable()
        port = _free_port()
        rpc.init_rpc("solo_t", rank=0, world_size=1,
                     master_endpoint=f"127.0.0.1:{port}")
        try:
            with tracing.span("t.rpc_async_root"):
                fut = rpc.rpc_async("solo_t", _double, args=(3,))
                assert fut.wait(timeout=30) == 6
        finally:
            rpc.shutdown()
        evs = tracing.events()
        root = [e for e in evs if e["name"] == "t.rpc_async_root"][0]
        client = [e for e in evs if e["name"] == "rpc.client"]
        assert len(client) == 1
        assert client[0]["trace_id"] == root["trace_id"]
        assert client[0]["parent_id"] == root["span_id"]

    def test_latency_histograms_and_request_counters(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.distributed import rpc
        obs.enable()
        srv, endpoint = rpc.serve()
        try:
            rpc.call_endpoint(endpoint, _double, args=(1,))
            with pytest.raises(ZeroDivisionError):
                rpc.call_endpoint(endpoint, _boom)
        finally:
            srv.shutdown()
            srv.server_close()
        snap = obs.snapshot()
        req = snap["paddle_tpu_rpc_requests_total"]["series"]
        assert req[("client", "ok")] == 1
        assert req[("client", "err")] == 1
        assert req[("server", "ok")] == 1
        assert req[("server", "err")] == 1
        assert snap["paddle_tpu_rpc_client_seconds"]["series"][()][
            "count"] == 2
        assert snap["paddle_tpu_rpc_server_seconds"]["series"][()][
            "count"] == 2

    def _rejected(self):
        from paddle_tpu import observability as obs
        snap = obs.snapshot().get(
            "paddle_tpu_rpc_rejected_frames_total", {"series": {}})
        return {k: v for k, v in snap["series"].items()}

    def test_bad_mac_frame_counted_and_logged(self, caplog):
        import logging
        import socket
        import struct
        from paddle_tpu.distributed import rpc
        srv, endpoint = rpc.serve()
        ip, port = endpoint.rsplit(":", 1)
        payload = b"not-a-real-pickle"
        frame = struct.pack("!Q", len(payload)) + b"\x00" * 32 + payload
        try:
            with caplog.at_level(
                    logging.WARNING, "paddle_tpu.distributed.rpc"):
                with socket.create_connection((ip, int(port)),
                                              timeout=10) as s:
                    s.sendall(frame)
                    # server drops the frame without replying: recv
                    # sees a clean close, never a pickle of our bytes
                    assert s.recv(1) == b""
        finally:
            srv.shutdown()
            srv.server_close()
        # counted regardless of the recording flag (obs is disabled
        # here), with the peer address in the log — auth misconfig is
        # distinguishable from network flake
        assert self._rejected().get(("bad_mac",)) == 1
        assert any("bad_mac" in r.message and "127.0.0.1" in r.message
                   for r in caplog.records)

    def test_short_frame_counted(self):
        import socket
        import struct
        import time as _time
        from paddle_tpu.distributed import rpc
        srv, endpoint = rpc.serve()
        ip, port = endpoint.rsplit(":", 1)
        try:
            with socket.create_connection((ip, int(port)),
                                          timeout=10) as s:
                s.sendall(struct.pack("!Q", 1 << 10))  # then hang up
            # the handler thread observes the close on its own
            # schedule — poll with a deadline, no fixed sleep
            deadline = _time.time() + 30.0
            while _time.time() < deadline and \
                    not self._rejected().get(("short_frame",)):
                _time.sleep(0.05)
        finally:
            srv.shutdown()
            srv.server_close()
        assert self._rejected().get(("short_frame",)) == 1


def test_two_process_rpc():
    port = _free_port()
    endpoint = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    script = WORKER.format(repo=REPO)
    procs = [subprocess.Popen([sys.executable, "-c", script, str(r),
                               endpoint],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, env=env, text=True)
             for r in (0, 1)]
    outs = [p.communicate(timeout=180)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    assert "RPC_OK" in outs[0]
