"""paddle.distributed.rpc conformance: in-process single-worker RPC and a
real two-process group over the master rendezvous (ref API:
python/paddle/distributed/rpc/rpc.py; test style: test/rpc/)."""
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _double(x):
    return 2 * x


def _boom():
    return 1 // 0


def test_single_worker_rpc_roundtrip():
    from paddle_tpu.distributed import rpc
    port = _free_port()
    rpc.init_rpc("solo", rank=0, world_size=1,
                 master_endpoint=f"127.0.0.1:{port}")
    try:
        assert rpc.rpc_sync("solo", _double, args=(21,)) == 42
        fut = rpc.rpc_async("solo", _double, args=(5,))
        assert fut.wait(timeout=30) == 10
        info = rpc.get_worker_info("solo")
        assert info.name == "solo" and info.rank == 0
        assert rpc.get_current_worker_info() == info
        assert [w.name for w in rpc.get_all_worker_infos()] == ["solo"]
        # remote exceptions propagate
        with pytest.raises(ZeroDivisionError):
            rpc.rpc_sync("solo", _boom)
    finally:
        rpc.shutdown()


WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    from paddle_tpu.distributed import rpc

    def mul(a, b):
        return a * b

    rank = int(sys.argv[1])
    rpc.init_rpc(f"worker{{rank}}".format(rank=rank), rank=rank,
                 world_size=2, master_endpoint=sys.argv[2])
    if rank == 0:
        out = rpc.rpc_sync("worker1", mul, args=(6, 7))
        assert out == 42, out
        futs = [rpc.rpc_async("worker1", mul, args=(i, i)) for i in range(4)]
        assert [f.wait() for f in futs] == [0, 1, 4, 9]
        print("RPC_OK")
    rpc.shutdown()
""")


def test_two_process_rpc():
    port = _free_port()
    endpoint = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    script = WORKER.format(repo=REPO)
    procs = [subprocess.Popen([sys.executable, "-c", script, str(r),
                               endpoint],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, env=env, text=True)
             for r in (0, 1)]
    outs = [p.communicate(timeout=180)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    assert "RPC_OK" in outs[0]
