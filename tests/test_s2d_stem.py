"""Space-to-depth ResNet stem (VERDICT r4 next-4): the 7x7/s2 3-channel
stem conv re-expressed as an IDENTICAL 4x4/s1 12-channel conv on a
half-resolution image (MXU lane utilization 3/128 -> 12/128; the MLPerf
TPU ResNet trick). ref: the reference's fused stem analog
paddle/fluid/operators/fused/cudnn_norm_conv.cu.h (CUDA-era fusion of
the same hot spot)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.vision.models import resnet50
from paddle_tpu.vision.models.resnet import ResNet, BottleneckBlock


def _pair(seed=0, **kw):
    pt.seed(seed)
    plain = ResNet(BottleneckBlock, 50, num_classes=10,
                   data_format="NHWC", **kw)
    pt.seed(seed)
    s2d = ResNet(BottleneckBlock, 50, num_classes=10, data_format="NHWC",
                 space_to_depth_stem=True, **kw)
    return plain, s2d


def test_stem_conv_identical():
    plain, s2d = _pair()
    x = pt.to_tensor(np.random.default_rng(0).standard_normal(
        (2, 64, 64, 3)).astype(np.float32))
    a = plain._stem_conv(x).numpy()
    b = s2d._stem_conv(x).numpy()
    assert a.shape == b.shape == (2, 32, 32, 64)
    np.testing.assert_allclose(a, b, atol=5e-6)


def test_full_model_identical_and_trainable():
    plain, s2d = _pair()
    plain.eval()
    s2d.eval()
    x = pt.to_tensor(np.random.default_rng(1).standard_normal(
        (2, 64, 64, 3)).astype(np.float32))
    np.testing.assert_allclose(plain(x).numpy(), s2d(x).numpy(),
                               atol=5e-5)
    # gradients flow through the on-the-fly weight transform into the
    # STANDARD [64, 3, 7, 7] conv1 weight (checkpoint layout unchanged)
    s2d.train()
    loss = (s2d(x) ** 2).mean()
    loss.backward()
    g = s2d.conv1.weight.grad
    assert g is not None and tuple(g.shape) == (64, 3, 7, 7)
    assert float(np.abs(g.numpy()).max()) > 0


def test_requires_nhwc():
    with pytest.raises(ValueError, match="NHWC"):
        resnet50(space_to_depth_stem=True)  # default NCHW
