"""Serving/decode fused-attention family conformance tests.

Each op is checked against a straightforward dense SDPA oracle computed
with numpy/jnp — the same strategy the reference uses in
test/legacy_test/test_block_multihead_attention.py (naive_attention_impl).
"""
import math

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate.nn.functional as F


def _sdpa(q, k, v, causal_offset=None, lens=None):
    """q: [B,H,Sq,D], k/v: [B,H,Sk,D] numpy f32. lens masks k columns.
    causal_offset: per-row int — k col j visible to q row i iff
    j <= i + off."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(D)
    mask = np.ones((B, Sq, Sk), bool)
    if lens is not None:
        mask &= np.arange(Sk)[None, None, :] < np.asarray(lens)[:, None, None]
    if causal_offset is not None:
        off = np.asarray(causal_offset).reshape(B, 1, 1)
        mask &= np.arange(Sk)[None, None, :] <= \
            np.arange(Sq)[None, :, None] + off
    s = np.where(mask[:, None], s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    p = np.nan_to_num(p)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


class TestMaskedMultiheadAttention:
    B, H, D, L = 2, 4, 16, 32

    def _mk(self, t_np, seed=0):
        rng = np.random.default_rng(seed)
        x = rng.standard_normal((self.B, 3 * self.H * self.D)).astype(
            np.float32)
        cache = rng.standard_normal(
            (2, self.B, self.H, self.L, self.D)).astype(np.float32)
        # zero out positions >= t so the oracle sees the same context
        for b, t in enumerate(t_np):
            cache[:, b, :, t:] = 0.0
        return x, cache

    def _oracle(self, x, cache, t_np):
        B, H, D, L = self.B, self.H, self.D, self.L
        qkv = x.reshape(B, 3, H, D)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        kc, vc = cache[0].copy(), cache[1].copy()
        for b, t in enumerate(t_np):
            kc[b, :, t] = k[b]
            vc[b, :, t] = v[b]
        out = _sdpa(q[:, :, None], kc, vc,
                    lens=np.asarray(t_np) + 1)
        return out[:, :, 0].reshape(B, H * D), np.stack([kc, vc])

    def test_matches_oracle_with_sequence_lengths(self):
        t_np = [5, 17]
        x, cache = self._mk(t_np)
        want_out, want_cache = self._oracle(x, cache, t_np)
        out, cache_out = F.masked_multihead_attention(
            paddle.to_tensor(x), paddle.to_tensor(cache),
            sequence_lengths=paddle.to_tensor(
                np.asarray(t_np, np.int32).reshape(-1, 1)))
        np.testing.assert_allclose(np.asarray(out._data), want_out,
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(cache_out._data),
                                   want_cache, rtol=1e-6, atol=1e-6)

    def test_src_mask_position_and_additive(self):
        t = 9
        x, cache = self._mk([t, t], seed=1)
        # additive src_mask covering prefix + self, one row half-masked
        sm = np.zeros((self.B, 1, 1, t + 1), np.float32)
        sm[1, 0, 0, :4] = -1e9
        out, _ = F.masked_multihead_attention(
            paddle.to_tensor(x), paddle.to_tensor(cache),
            src_mask=paddle.to_tensor(sm))
        qkv = x.reshape(self.B, 3, self.H, self.D)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        kc, vc = cache[0].copy(), cache[1].copy()
        kc[:, :, t] = k
        vc[:, :, t] = v
        # oracle: rows 4.. only for batch 1
        kc1, vc1 = kc.copy(), vc.copy()
        want0 = _sdpa(q[0:1, :, None], kc1[0:1], vc1[0:1],
                      lens=[t + 1])[0, :, 0]
        want1 = _sdpa(q[1:2, :, None, :],
                      kc1[1:2, :, 4:t + 1], vc1[1:2, :, 4:t + 1])[0, :, 0]
        got = np.asarray(out._data).reshape(self.B, self.H, self.D)
        np.testing.assert_allclose(got[0], want0, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(got[1], want1, rtol=1e-5, atol=1e-5)

    def test_rotary(self):
        t_np = [3, 3]
        x, cache = self._mk(t_np, seed=2)
        D = self.D
        inv = 1.0 / (10000 ** (np.arange(0, D, 2) / D))
        pos = np.arange(self.L)[:, None] * inv[None, :]
        rt = np.zeros((self.B, 1, 1, self.L, D), np.float32)
        rt[:, 0, 0, :, : D // 2] = np.cos(pos)
        rt[:, 0, 0, :, D // 2:] = np.sin(pos)
        out, _ = F.masked_multihead_attention(
            paddle.to_tensor(x), paddle.to_tensor(cache),
            sequence_lengths=paddle.to_tensor(
                np.asarray(t_np, np.int32).reshape(-1, 1)),
            rotary_tensor=paddle.to_tensor(rt), rotary_emb_dims=1)
        assert np.isfinite(np.asarray(out._data)).all()
        # neox style differs from interleaved on the same inputs
        out2, _ = F.masked_multihead_attention(
            paddle.to_tensor(x), paddle.to_tensor(cache),
            sequence_lengths=paddle.to_tensor(
                np.asarray(t_np, np.int32).reshape(-1, 1)),
            rotary_tensor=paddle.to_tensor(rt), rotary_emb_dims=1,
            use_neox_rotary_style=True)
        assert not np.allclose(np.asarray(out._data),
                               np.asarray(out2._data))

    def test_quant_args_raise(self):
        x, cache = self._mk([1, 1])
        with pytest.raises(NotImplementedError):
            F.masked_multihead_attention(
                paddle.to_tensor(x), paddle.to_tensor(cache),
                sequence_lengths=paddle.to_tensor(
                    np.ones((2, 1), np.int32)),
                qkv_out_scale=paddle.to_tensor(np.ones(3, np.float32)))


def _mk_block_inputs(lens_this_time, dec_lens, kvH, H, D, bs, npb,
                     seed=0):
    """Build packed qkv + paged caches for a batch of rows."""
    rng = np.random.default_rng(seed)
    B = len(lens_this_time)
    T = int(sum(lens_this_time))
    qkv = rng.standard_normal((T, (H + 2 * kvH) * D)).astype(np.float32)
    nb = B * npb + 1
    kcache = np.zeros((nb, kvH, bs, D), np.float32)
    vcache = np.zeros((nb, kvH, bs, D), np.float32)
    tbl = -np.ones((B, npb), np.int32)
    for b in range(B):
        for p in range(npb):
            tbl[b, p] = 1 + b * npb + p  # block 0 left as garbage trap
    cu = np.zeros(B + 1, np.int32)
    cu[1:] = np.cumsum(lens_this_time)
    return qkv, kcache, vcache, tbl, cu


class TestBlockMultiheadAttention:
    def test_prefill_matches_causal_sdpa(self):
        B, H, kvH, D, bs, npb, S = 2, 4, 4, 16, 8, 4, 10
        qkv, kc, vc, tbl, cu = _mk_block_inputs([S, S], [0, 0],
                                                kvH, H, D, bs, npb)
        enc = np.full((B, 1), S, np.int32)
        dec = np.zeros((B, 1), np.int32)
        stt = np.full((B, 1), S, np.int32)
        out, _, kco, vco = F.block_multihead_attention(
            paddle.to_tensor(qkv), paddle.to_tensor(kc),
            paddle.to_tensor(vc), paddle.to_tensor(enc),
            paddle.to_tensor(dec), paddle.to_tensor(stt),
            None, None, paddle.to_tensor(cu), paddle.to_tensor(cu),
            paddle.to_tensor(tbl), max_seq_len=S, block_size=bs)
        # oracle
        q = qkv[:, :H * D].reshape(T := 2 * S, H, D)
        k = qkv[:, H * D:(H + kvH) * D].reshape(T, kvH, D)
        v = qkv[:, (H + kvH) * D:].reshape(T, kvH, D)
        for b in range(B):
            qb = np.transpose(q[b * S:(b + 1) * S], (1, 0, 2))[None]
            kb = np.transpose(k[b * S:(b + 1) * S], (1, 0, 2))[None]
            vb = np.transpose(v[b * S:(b + 1) * S], (1, 0, 2))[None]
            want = _sdpa(qb, kb, vb, causal_offset=[0])[0]  # [H,S,D]
            got = np.asarray(out._data)[b * S:(b + 1) * S].reshape(
                S, H, D).transpose(1, 0, 2)
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
        # cache got the k/v tokens at the right pages
        kcon = np.asarray(kco._data)
        for b in range(B):
            for i in range(S):
                blk, slot = tbl[b, i // bs], i % bs
                np.testing.assert_allclose(
                    kcon[blk, :, slot], k[b * S + i], rtol=1e-6)

    def test_decode_step_appends_and_attends(self):
        B, H, kvH, D, bs, npb = 2, 4, 2, 8, 4, 3   # GQA 2:1
        prior = [5, 9]
        qkv, kc, vc, tbl, cu = _mk_block_inputs(
            [1, 1], prior, kvH, H, D, bs, npb, seed=3)
        rng = np.random.default_rng(7)
        # pre-populate caches with the prior tokens
        hist_k = rng.standard_normal((B, max(prior), kvH, D)).astype(
            np.float32)
        hist_v = rng.standard_normal((B, max(prior), kvH, D)).astype(
            np.float32)
        for b in range(B):
            for i in range(prior[b]):
                kc[tbl[b, i // bs], :, i % bs] = hist_k[b, i]
                vc[tbl[b, i // bs], :, i % bs] = hist_v[b, i]
        enc = np.zeros((B, 1), np.int32)
        dec = np.asarray(prior, np.int32).reshape(B, 1)
        stt = np.ones((B, 1), np.int32)
        out, _, kco, vco = F.block_multihead_attention(
            paddle.to_tensor(qkv), paddle.to_tensor(kc),
            paddle.to_tensor(vc), paddle.to_tensor(enc),
            paddle.to_tensor(dec), paddle.to_tensor(stt),
            None, None, paddle.to_tensor(cu), paddle.to_tensor(cu),
            paddle.to_tensor(tbl), max_seq_len=1, block_size=bs)
        q = qkv[:, :H * D].reshape(B, H, D)
        knew = qkv[:, H * D:(H + kvH) * D].reshape(B, kvH, D)
        vnew = qkv[:, (H + kvH) * D:].reshape(B, kvH, D)
        got = np.asarray(out._data).reshape(B, H, D)
        for b in range(B):
            ctx_k = np.concatenate([hist_k[b, :prior[b]],
                                    knew[b][None]], 0)  # [t+1,kvH,D]
            ctx_v = np.concatenate([hist_v[b, :prior[b]],
                                    vnew[b][None]], 0)
            rep = H // kvH
            ck = np.repeat(np.transpose(ctx_k, (1, 0, 2)), rep, 0)[None]
            cv = np.repeat(np.transpose(ctx_v, (1, 0, 2)), rep, 0)[None]
            want = _sdpa(q[b][None, :, None], ck, cv)[0, :, 0]
            np.testing.assert_allclose(got[b], want, rtol=1e-4,
                                       atol=1e-4)

    def test_rope_changes_output(self):
        B, H, kvH, D, bs, npb, S = 1, 2, 2, 8, 4, 2, 4
        qkv, kc, vc, tbl, cu = _mk_block_inputs([S], [0], kvH, H, D,
                                                bs, npb)
        enc = np.full((B, 1), S, np.int32)
        dec = np.zeros((B, 1), np.int32)
        stt = np.full((B, 1), S, np.int32)
        rope = np.zeros((2, B, bs * npb, 1, D // 2), np.float32)
        inv = 1.0 / (10000 ** (np.arange(0, D, 2) / D))
        pos = np.arange(bs * npb)[:, None] * inv[None, :]
        rope[0, :, :, 0] = np.cos(pos)
        rope[1, :, :, 0] = np.sin(pos)
        args = (paddle.to_tensor(qkv), paddle.to_tensor(kc),
                paddle.to_tensor(vc), paddle.to_tensor(enc),
                paddle.to_tensor(dec), paddle.to_tensor(stt),
                None, None, paddle.to_tensor(cu), paddle.to_tensor(cu),
                paddle.to_tensor(tbl))
        base, *_ = F.block_multihead_attention(
            *args, max_seq_len=S, block_size=bs)
        roped, *_ = F.block_multihead_attention(
            *args, rope_emb=paddle.to_tensor(rope), max_seq_len=S,
            block_size=bs)
        assert not np.allclose(np.asarray(base._data),
                               np.asarray(roped._data))


class TestVariableLengthMemEffAttention:
    def test_matches_masked_sdpa(self):
        B, H, S, D = 3, 2, 12, 8
        rng = np.random.default_rng(0)
        q = rng.standard_normal((B, H, S, D)).astype(np.float32)
        k = rng.standard_normal((B, H, S, D)).astype(np.float32)
        v = rng.standard_normal((B, H, S, D)).astype(np.float32)
        lens = np.asarray([12, 7, 3], np.int32)
        out = F.variable_length_memory_efficient_attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v), paddle.to_tensor(lens.reshape(-1, 1)),
            paddle.to_tensor(lens.reshape(-1, 1)))
        want = _sdpa(q, k, v, lens=lens)
        got = np.asarray(out._data)
        for b in range(B):
            L = lens[b]
            np.testing.assert_allclose(got[b, :, :L], want[b, :, :L],
                                       rtol=1e-4, atol=1e-4)
            np.testing.assert_allclose(got[b, :, L:], 0.0)

    def test_causal_and_additive_mask(self):
        B, H, S, D = 1, 2, 6, 4
        rng = np.random.default_rng(1)
        q = rng.standard_normal((B, H, S, D)).astype(np.float32)
        k = rng.standard_normal((B, H, S, D)).astype(np.float32)
        v = rng.standard_normal((B, H, S, D)).astype(np.float32)
        lens = np.full((B, 1), S, np.int32)
        out = F.variable_length_memory_efficient_attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v), paddle.to_tensor(lens),
            paddle.to_tensor(lens), causal=True)
        want = _sdpa(q, k, v, causal_offset=[0], lens=[S])
        np.testing.assert_allclose(np.asarray(out._data), want,
                                   rtol=1e-4, atol=1e-4)
        # additive mask path
        m = np.zeros((B, 1, S, S), np.float32)
        m[:, :, :, 0] = -1e9
        out2 = F.variable_length_memory_efficient_attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v), paddle.to_tensor(lens),
            paddle.to_tensor(lens), mask=paddle.to_tensor(m))
        s = np.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(D) + m
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        want2 = np.einsum("bhqk,bhkd->bhqd", p, v)
        np.testing.assert_allclose(np.asarray(out2._data), want2,
                                   rtol=1e-4, atol=1e-4)


class TestFusedMultiTransformer:
    def _mk_weights(self, nlayers, dm, H, D, ffn, seed=0):
        rng = np.random.default_rng(seed)
        t = paddle.to_tensor

        def g(*shape):
            return t((rng.standard_normal(shape) * 0.05).astype(
                np.float32))

        w = dict(
            ln_scales=[t(np.ones(dm, np.float32))] * nlayers,
            ln_biases=[t(np.zeros(dm, np.float32))] * nlayers,
            qkv_weights=[g(3, H, D, dm) for _ in range(nlayers)],
            qkv_biases=[g(3, H, D) for _ in range(nlayers)],
            linear_weights=[g(H * D, dm) for _ in range(nlayers)],
            linear_biases=[g(dm) for _ in range(nlayers)],
            ffn_ln_scales=[t(np.ones(dm, np.float32))] * nlayers,
            ffn_ln_biases=[t(np.zeros(dm, np.float32))] * nlayers,
            ffn1_weights=[g(dm, ffn) for _ in range(nlayers)],
            ffn1_biases=[g(ffn) for _ in range(nlayers)],
            ffn2_weights=[g(ffn, dm) for _ in range(nlayers)],
            ffn2_biases=[g(dm) for _ in range(nlayers)],
        )
        return w

    def test_prefill_then_decode_matches_full_forward(self):
        """Decode steps through the cache must reproduce the full
        (no-cache) forward logits — THE serving-correctness property."""
        nlayers, dm, H, D, ffn = 2, 32, 4, 8, 64
        B, S, L = 2, 5, 12
        w = self._mk_weights(nlayers, dm, H, D, ffn)
        rng = np.random.default_rng(5)
        seq = rng.standard_normal((B, S + 2, dm)).astype(np.float32)

        # full forward over S+2 tokens, no cache (causal)
        full = F.fused_multi_transformer(
            paddle.to_tensor(seq), **w)
        full_np = np.asarray(full._data)

        # prefill S tokens, then decode 2 more
        caches = [paddle.to_tensor(np.zeros((2, B, H, L, D), np.float32))
                  for _ in range(nlayers)]
        out, caches = F.fused_multi_transformer(
            paddle.to_tensor(seq[:, :S]), cache_kvs=caches, **w)
        np.testing.assert_allclose(np.asarray(out._data),
                                   full_np[:, :S], rtol=1e-4, atol=1e-4)
        for step in range(2):
            out, caches = F.fused_multi_transformer(
                paddle.to_tensor(seq[:, S + step:S + step + 1]),
                cache_kvs=caches,
                time_step=paddle.to_tensor(
                    np.asarray(S + step, np.int32)), **w)
            np.testing.assert_allclose(
                np.asarray(out._data)[:, 0], full_np[:, S + step],
                rtol=1e-4, atol=1e-4,
                err_msg=f"decode step {step} diverged from full forward")

    def test_post_layer_norm_and_relu(self):
        nlayers, dm, H, D, ffn = 1, 16, 2, 8, 32
        w = self._mk_weights(nlayers, dm, H, D, ffn, seed=9)
        x = paddle.to_tensor(np.random.default_rng(0).standard_normal(
            (1, 3, dm)).astype(np.float32))
        out = F.fused_multi_transformer(
            x, pre_layer_norm=False, activation="relu", **w)
        assert np.isfinite(np.asarray(out._data)).all()


class TestFusedLayerClasses:
    """incubate.nn Layer wrappers (ref: incubate/nn/layer/
    fused_transformer.py) route through the same fused functionals."""

    def test_fused_mha_and_encoder_layer(self):
        import paddle_tpu as pt
        from paddle_tpu.incubate.nn import (FusedMultiHeadAttention,
                                            FusedTransformerEncoderLayer)
        pt.seed(0)
        mha = FusedMultiHeadAttention(32, 4, dropout_rate=0.0,
                                      attn_dropout_rate=0.0)
        mha.eval()
        x = pt.to_tensor(np.random.default_rng(0).standard_normal(
            (2, 5, 32)).astype(np.float32))
        out = mha(x)
        out = out[0] if isinstance(out, tuple) else out
        assert out.numpy().shape == (2, 5, 32)
        enc = FusedTransformerEncoderLayer(32, 4, 64, dropout_rate=0.0)
        enc.eval()
        out = enc(x)
        out = out[0] if isinstance(out, tuple) else out
        assert out.numpy().shape == (2, 5, 32)
        assert np.isfinite(out.numpy()).all()

    def test_fused_multi_transformer_layer_decode(self):
        import paddle_tpu as pt
        from paddle_tpu.incubate.nn import FusedMultiTransformer
        pt.seed(1)
        B, S, L, dm, H = 2, 4, 10, 32, 4
        m = FusedMultiTransformer(dm, H, 64, num_layers=2)
        m.eval()
        rng = np.random.default_rng(2)
        seq = pt.to_tensor(rng.standard_normal((B, S + 1, dm))
                           .astype(np.float32))
        full = m(pt.to_tensor(np.asarray(seq._data)))
        full = full[0] if isinstance(full, tuple) else full
        caches = [pt.to_tensor(np.zeros((2, B, H, L, dm // H),
                                        np.float32)) for _ in range(2)]
        out, caches = m(pt.to_tensor(np.asarray(seq._data)[:, :S]),
                        caches=caches)
        np.testing.assert_allclose(out.numpy(),
                                   full.numpy()[:, :S], rtol=1e-4,
                                   atol=1e-4)
        step, caches = m(pt.to_tensor(np.asarray(seq._data)[:, S:S + 1]),
                         caches=caches,
                         time_step=pt.to_tensor(np.asarray(S, np.int32)))
        np.testing.assert_allclose(step.numpy()[:, 0],
                                   full.numpy()[:, S], rtol=1e-4,
                                   atol=1e-4)


class TestIncubateFunctionalBatch:
    """Round-4 tail of incubate.nn.functional (ref: fused_matmul_bias,
    fused_dot_product_attention, fused_ec_moe, fused_gate_attention)."""

    def test_fused_matmul_bias(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((3, 4)).astype(np.float32)
        y = rng.standard_normal((5, 4)).astype(np.float32)
        b = rng.standard_normal((5,)).astype(np.float32)
        out = F.fused_matmul_bias(paddle.to_tensor(x),
                                  paddle.to_tensor(y),
                                  paddle.to_tensor(b), transpose_y=True)
        np.testing.assert_allclose(np.asarray(out._data), x @ y.T + b,
                                   rtol=1e-5)

    def test_fused_dot_product_attention(self):
        rng = np.random.default_rng(1)
        B, S, H, D = 2, 6, 2, 8
        q = rng.standard_normal((B, S, H, D)).astype(np.float32)
        k = rng.standard_normal((B, S, H, D)).astype(np.float32)
        v = rng.standard_normal((B, S, H, D)).astype(np.float32)
        out = F.fused_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v), is_training=False,
            is_causal_masking=True)
        qh = np.transpose(q, (0, 2, 1, 3))
        kh = np.transpose(k, (0, 2, 1, 3))
        vh = np.transpose(v, (0, 2, 1, 3))
        want = _sdpa(qh, kh, vh, causal_offset=[0, 0])
        np.testing.assert_allclose(np.asarray(out._data),
                                   np.transpose(want, (0, 2, 1, 3)),
                                   rtol=1e-4, atol=1e-5)

    def test_fused_ec_moe_mixes_experts(self):
        rng = np.random.default_rng(2)
        B, S, dm, ff, E = 2, 3, 8, 16, 4
        x = rng.standard_normal((B, S, dm)).astype(np.float32)
        w0 = rng.standard_normal((E, dm, ff)).astype(np.float32) * 0.1
        b0 = rng.standard_normal((E, 1, ff)).astype(np.float32) * 0.1
        w1 = rng.standard_normal((E, ff, dm)).astype(np.float32) * 0.1
        b1 = rng.standard_normal((E, 1, dm)).astype(np.float32) * 0.1
        # one-hot gate on expert j == plain FFN_j
        for j in (0, 3):
            gate = np.full((B, S, E), -1e9, np.float32)
            gate[..., j] = 0.0
            out = F.fused_ec_moe(
                paddle.to_tensor(x), paddle.to_tensor(gate),
                paddle.to_tensor(w0), paddle.to_tensor(b0),
                paddle.to_tensor(w1), paddle.to_tensor(b1), "relu")
            h = np.maximum(x @ w0[j] + b0[j], 0.0)
            want = h @ w1[j] + b1[j]
            np.testing.assert_allclose(np.asarray(out._data), want,
                                       rtol=1e-4, atol=1e-5)

    def test_fused_gate_attention(self):
        rng = np.random.default_rng(3)
        N, B, Q, A, H, C = 1, 2, 4, 8, 2, 4
        qd = rng.standard_normal((N, B, Q, A)).astype(np.float32)
        qkvw = rng.standard_normal((3, H, C, A)).astype(np.float32) * 0.3
        gw = rng.standard_normal((A, H, C)).astype(np.float32) * 0.3
        gb = np.zeros((H, C), np.float32)
        ow = rng.standard_normal((H, C, A)).astype(np.float32) * 0.3
        ob = np.zeros((A,), np.float32)
        out = F.fused_gate_attention(
            paddle.to_tensor(qd), qkv_weight=paddle.to_tensor(qkvw),
            gate_linear_weight=paddle.to_tensor(gw),
            gate_linear_bias=paddle.to_tensor(gb),
            out_linear_weight=paddle.to_tensor(ow),
            out_linear_bias=paddle.to_tensor(ob))
        assert out.numpy().shape == (N, B, Q, A)
        # numpy oracle of the documented pseudo-code
        c = C ** -0.5
        q = np.einsum("nbqa,hca->nbqhc", qd, qkvw[0]) * c
        k = np.einsum("nbka,hca->nbkhc", qd, qkvw[1])
        v = np.einsum("nbka,hca->nbkhc", qd, qkvw[2])
        logits = np.einsum("nbqhc,nbkhc->nbhqk", q, k)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        w = e / e.sum(-1, keepdims=True)
        avg = np.einsum("nbhqk,nbkhc->nbqhc", w, v)
        gate = 1.0 / (1.0 + np.exp(-(np.einsum("nbqa,ahc->nbqhc", qd,
                                               gw) + gb)))
        want = np.einsum("nbqhc,hco->nbqo", avg * gate, ow) + ob
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-4,
                                   atol=1e-5)


def test_incubate_layer_wrappers():
    """FusedLinear / FusedDropoutAdd / FusedEcMoe layer classes +
    identity_loss (ref: incubate/nn/layer/*, loss.py:21)."""
    import paddle_tpu as pt
    import paddle_tpu.incubate.nn as N
    rng = np.random.default_rng(0)
    x = pt.to_tensor(rng.standard_normal((2, 4)).astype(np.float32))
    lin = N.FusedLinear(4, 8)
    out = lin(x)
    np.testing.assert_allclose(
        out.numpy(),
        np.asarray(x._data) @ np.asarray(lin.weight._data)
        + np.asarray(lin.bias._data), rtol=1e-5)
    # transpose_weight layout
    lt = N.FusedLinear(4, 8, transpose_weight=True)
    assert list(lt.weight.shape) == [8, 4]
    assert lt(x).numpy().shape == (2, 8)
    da = N.FusedDropoutAdd(p=0.0)
    np.testing.assert_allclose(da(x, x).numpy(),
                               2 * np.asarray(x._data), rtol=1e-6)
    moe = N.FusedEcMoe(4, 16, 3, act_type="relu")
    x3 = pt.to_tensor(rng.standard_normal((2, 5, 4)).astype(np.float32))
    g = pt.to_tensor(rng.standard_normal((2, 5, 3)).astype(np.float32))
    assert moe(x3, g).numpy().shape == (2, 5, 4)
    np.testing.assert_allclose(
        float(N.identity_loss(x, "sum").numpy()),
        np.asarray(x._data).sum(), rtol=1e-5)
    assert N.identity_loss(x, "none") is x


class TestMemoryEfficientAttention:
    """incubate.nn.memory_efficient_attention + attn_bias classes
    (ref: memory_efficient_attention.py:70, attn_bias.py)."""

    def _qkv(self, b=2, s=8, h=2, d=4, seed=0):
        rng = np.random.default_rng(seed)
        mk = lambda: rng.standard_normal((b, s, h, d)).astype(np.float32)
        return mk(), mk(), mk()

    def _oracle(self, q, k, v, keep):
        qh = np.transpose(q, (0, 2, 1, 3))
        kh = np.transpose(k, (0, 2, 1, 3))
        vh = np.transpose(v, (0, 2, 1, 3))
        s = np.einsum("bhqd,bhkd->bhqk", qh, kh) / math.sqrt(q.shape[-1])
        s = np.where(keep[None, None], s, -np.inf)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = np.nan_to_num(p / p.sum(-1, keepdims=True))
        return np.transpose(np.einsum("bhqk,bhkd->bhqd", p, vh),
                            (0, 2, 1, 3))

    def test_causal_mask_class(self):
        from paddle_tpu.incubate.nn import memory_efficient_attention
        from paddle_tpu.incubate.nn.attn_bias import LowerTriangularMask
        q, k, v = self._qkv()
        out = memory_efficient_attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v), attn_bias=LowerTriangularMask())
        keep = np.tril(np.ones((8, 8), bool))
        np.testing.assert_allclose(out.numpy(),
                                   self._oracle(q, k, v, keep),
                                   rtol=1e-4, atol=1e-5)

    def test_block_diagonal_masks(self):
        from paddle_tpu.incubate.nn import memory_efficient_attention
        from paddle_tpu.incubate.nn.attn_bias import BlockDiagonalMask
        q, k, v = self._qkv(b=1)
        bias = BlockDiagonalMask.from_seqlens([3, 5])
        out = memory_efficient_attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v), attn_bias=bias)
        seg = np.asarray([0] * 3 + [1] * 5)
        keep = seg[:, None] == seg[None, :]
        np.testing.assert_allclose(out.numpy(),
                                   self._oracle(q, k, v, keep),
                                   rtol=1e-4, atol=1e-5)
        causal = bias.make_causal()
        out = memory_efficient_attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v), attn_bias=causal)
        keep = keep & np.tril(np.ones((8, 8), bool))
        np.testing.assert_allclose(out.numpy(),
                                   self._oracle(q, k, v, keep),
                                   rtol=1e-4, atol=1e-5)

    def test_tensor_bias(self):
        from paddle_tpu.incubate.nn import memory_efficient_attention
        from paddle_tpu.incubate.nn.attn_bias import (
            LowerTriangularMaskWithTensorBias)
        q, k, v = self._qkv(seed=2)
        rng = np.random.default_rng(3)
        bias = rng.standard_normal((2, 2, 8, 8)).astype(np.float32)
        out = memory_efficient_attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v),
            attn_bias=LowerTriangularMaskWithTensorBias(
                paddle.to_tensor(bias)))
        qh = np.transpose(q, (0, 2, 1, 3))
        kh = np.transpose(k, (0, 2, 1, 3))
        vh = np.transpose(v, (0, 2, 1, 3))
        s = np.einsum("bhqd,bhkd->bhqk", qh, kh) / math.sqrt(4) + bias
        s = np.where(np.tril(np.ones((8, 8), bool))[None, None], s,
                     -np.inf)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = np.nan_to_num(p / p.sum(-1, keepdims=True))
        want = np.transpose(np.einsum("bhqk,bhkd->bhqd", p, vh),
                            (0, 2, 1, 3))
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-4,
                                   atol=1e-5)


def test_block_causal_heterogeneous_packing():
    """Per-block causal with DIFFERENT q/kv packings (the case a global
    diagonal gets wrong): q blocks [2,6], kv blocks [6,2]."""
    import math as _m
    from paddle_tpu.incubate.nn import memory_efficient_attention
    from paddle_tpu.incubate.nn.attn_bias import BlockDiagonalMask
    rng = np.random.default_rng(5)
    q = rng.standard_normal((1, 8, 2, 4)).astype(np.float32)
    k = rng.standard_normal((1, 8, 2, 4)).astype(np.float32)
    v = rng.standard_normal((1, 8, 2, 4)).astype(np.float32)
    bias = BlockDiagonalMask.from_seqlens([2, 6], [6, 2]).make_causal()
    out = memory_efficient_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        attn_bias=bias)
    # oracle: per-block local causal
    qseg = np.asarray([0] * 2 + [1] * 6)
    kseg = np.asarray([0] * 6 + [1] * 2)
    qloc = np.arange(8) - np.asarray([0, 2])[qseg]
    kloc = np.arange(8) - np.asarray([0, 6])[kseg]
    keep = (qseg[:, None] == kseg[None, :]) & \
        (kloc[None, :] <= qloc[:, None])
    qh = np.transpose(q, (0, 2, 1, 3))
    kh = np.transpose(k, (0, 2, 1, 3))
    vh = np.transpose(v, (0, 2, 1, 3))
    s = np.einsum("bhqd,bhkd->bhqk", qh, kh) / _m.sqrt(4)
    s = np.where(keep[None, None], s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = np.nan_to_num(p / p.sum(-1, keepdims=True))
    want = np.transpose(np.einsum("bhqk,bhkd->bhqd", p, vh),
                        (0, 2, 1, 3))
    np.testing.assert_allclose(out.numpy(), want, rtol=1e-4, atol=1e-5)
    # q row 2 (block 1 local 0) must attend ONLY kv col 6 (its block's
    # first key) — the global-diagonal bug made this row fully masked
    assert keep[2].sum() == 1 and keep[2, 6]


def test_block_mask_rejects_short_packing():
    from paddle_tpu.incubate.nn.attn_bias import BlockDiagonalMask
    bias = BlockDiagonalMask.from_seqlens([3, 4])
    with pytest.raises(ValueError):
        bias.materialize((1, 1, 8, 8))
