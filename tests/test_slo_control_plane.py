"""Serving SLO control plane (observability/slo_fleet.py,
inference/autoscaler.py, inference/traffic.py + the router's elastic
surface): fleet-wide SLO evaluation over process-merged request
series, the TTFT latency-budget invariant, the SLO-driven autoscaler's
hysteresis/journal/bundle contract, and the deterministic traffic
harness.

Oracles: the TTFT budget components must sum EXACTLY to the TTFT
observation (both sides are computed from the same perf_counter reads,
so equality is bitwise, not approximate); the fleet monitor's windowed
attained fractions against hand-built bucket vectors; the autoscaler
against a scripted monitor (every decision's cause is pinned)."""
import json
import multiprocessing
import os
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.inference import (Autoscaler, LLMEngine, Router,
                                  RouterActuator, TrafficModel,
                                  run_traffic)
from paddle_tpu.models import GPTForCausalLM
from paddle_tpu.models.gpt import gpt_tiny
from paddle_tpu.observability import flight
from paddle_tpu.observability import metrics as om
from paddle_tpu.observability import slo, slo_fleet


@pytest.fixture(scope="module")
def tiny_gpt():
    pt.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.fixture(autouse=True)
def _clean():
    obs.disable()
    obs.reset()
    flight.disarm()
    yield
    flight.disarm()
    obs.disable()
    obs.reset()


def _engine_factory(model):
    def make(_i):
        return LLMEngine(model, max_batch=2, block_size=16,
                         decode_chunk=4, prompt_quantum=16,
                         max_model_len=64)
    return make


def _prompts(n=4, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 1024, (k,)).astype(np.int32)
            for k in (5, 9, 13, 21, 7, 15)[:n]]


# ---------------------------------------------------------------------------
# TTFT latency budget: components sum exactly to TTFT
# ---------------------------------------------------------------------------
class TestTTFTBudget:
    BUDGET_COMPONENTS = {"queue_wait", "prefill_compute",
                         "affinity_miss", "compile_stall", "other"}

    def test_components_sum_exactly_to_ttft(self, tiny_gpt):
        obs.enable()
        eng = _engine_factory(tiny_gpt)(0)
        eng.generate(_prompts(4), max_new_tokens=6)
        r = om.registry()
        ttft = r.get("paddle_tpu_request_ttft_seconds")
        child = ttft._children.get(())
        assert child is not None and child._count == 4
        bud = r.get("paddle_tpu_request_ttft_budget_seconds")
        comps = {key[0]: c for key, c in bud._series()}
        # every observed component is a known one, and the two big
        # mandatory ones are always present
        assert set(comps) <= self.BUDGET_COMPONENTS
        assert {"queue_wait", "prefill_compute"} <= set(comps)
        # the invariant the dashboards divide by: component sums ==
        # TTFT sum EXACTLY (same perf_counter reads on both sides,
        # the remainder lands in "other" by construction)
        total = sum(c._sum for c in comps.values())
        assert total == pytest.approx(child._sum, abs=1e-9)
        # per-request observation parity on every observed component:
        # one observation per request
        for name, c in comps.items():
            assert c._count == child._count, name

    def test_budget_empty_when_disabled(self, tiny_gpt):
        assert not obs.enabled()
        eng = _engine_factory(tiny_gpt)(0)
        eng.generate(_prompts(2), max_new_tokens=4)
        bud = om.registry().get(
            "paddle_tpu_request_ttft_budget_seconds")
        if bud is not None:     # registered at import, never observed
            assert sum(c._count for _, c in bud._series()) == 0


# ---------------------------------------------------------------------------
# FleetSLOMonitor: windowed verdicts, episode latch, attribution
# ---------------------------------------------------------------------------
def _proc_hist(reg):
    return reg.histogram("paddle_tpu_request_ttft_seconds",
                         "test ttft", ("process",))


class TestFleetSLOMonitor:
    def _rule(self, thr=0.5, objective=0.9):
        return slo.SLO("ttft_p95", "paddle_tpu_request_ttft_seconds",
                       threshold_s=thr, objective=objective)

    def test_fleet_sum_and_worst_process_attribution(self):
        obs.enable()
        reg = om.MetricsRegistry()      # aggregator-style registry
        h = _proc_hist(reg)
        for _ in range(40):
            h.labels(process="fast").observe(0.01)
        for _ in range(40):
            h.labels(process="slow").observe(2.0)
        mon = slo_fleet.FleetSLOMonitor(
            registry=reg, rules=[self._rule()],
            flight_on_breach=False)
        (res,) = mon.evaluate()
        assert not res.ok and res.count == 80
        assert res.attained == pytest.approx(0.5, abs=0.05)
        assert res.worst_process == "slow"
        assert res.per_process["fast"] == pytest.approx(1.0, abs=0.02)
        assert res.per_process["slow"] == pytest.approx(0.0, abs=0.02)
        # verdict gauges published into the evaluated registry
        snap = reg.snapshot()
        assert snap["paddle_tpu_slo_attained_fraction"]["series"][
            ("ttft_p95",)] == res.attained
        assert snap["paddle_tpu_slo_objective_fraction"]["series"][
            ("ttft_p95",)] == 0.9

    def test_windowed_delta_sees_only_new_observations(self):
        obs.enable()
        reg = om.MetricsRegistry()
        h = _proc_hist(reg)
        for _ in range(50):
            h.labels(process="p0").observe(2.0)    # breaching history
        mon = slo_fleet.FleetSLOMonitor(
            registry=reg, rules=[self._rule()],
            flight_on_breach=False)
        (r1,) = mon.evaluate()
        assert not r1.ok and r1.count == 50
        # window 2: only fast traffic arrives — the cumulative
        # distribution is still poisoned, the window is clean
        for _ in range(50):
            h.labels(process="p0").observe(0.01)
        (r2,) = mon.evaluate()
        assert r2.ok and r2.count == 50
        assert r2.attained == pytest.approx(1.0, abs=0.02)
        # idle window: vacuous, not a breach
        (r3,) = mon.evaluate()
        assert r3.ok and r3.attained is None and r3.count == 0

    def test_min_count_makes_thin_windows_vacuous(self):
        obs.enable()
        reg = om.MetricsRegistry()
        h = _proc_hist(reg)
        mon = slo_fleet.FleetSLOMonitor(
            registry=reg, rules=[self._rule()],
            min_count=5, flight_on_breach=False)
        mon.evaluate()
        h.labels(process="p0").observe(2.0)
        (res,) = mon.evaluate()
        assert res.ok and res.attained is None

    def test_breach_episode_dumps_one_bundle(self, tmp_path):
        obs.enable()
        flight.arm(str(tmp_path))
        reg = om.MetricsRegistry()
        h = _proc_hist(reg)
        mon = slo_fleet.FleetSLOMonitor(
            registry=reg, rules=[self._rule()])
        mon.evaluate()                      # prime the window

        def bundles():
            return sorted(p for p in os.listdir(str(tmp_path))
                          if p.startswith("bundle_"))

        for _ in range(20):
            h.labels(process="slow").observe(2.0)
        mon.evaluate()                      # ok -> breach: one bundle
        assert len(bundles()) == 1
        assert "slo_breach" in bundles()[0]
        for _ in range(20):
            h.labels(process="slow").observe(2.0)
        mon.evaluate()                      # still breaching: latched
        assert len(bundles()) == 1
        for _ in range(60):
            h.labels(process="slow").observe(0.01)
        mon.evaluate()                      # recovered
        for _ in range(20):
            h.labels(process="slow").observe(2.0)
        mon.evaluate()                      # NEW episode: second bundle
        assert len(bundles()) == 2
        # the bundle's detail attributes the breach
        with open(os.path.join(str(tmp_path), bundles()[0],
                               "meta.json")) as f:
            meta = json.load(f)
        assert meta["reason"] == "slo_breach"
        assert meta["detail"]["worst_process"] == "slow"
        assert meta["detail"]["scope"] == "fleet"
        assert meta["detail"]["threshold_s"] == 0.5
        # breaches_total counts EVALUATIONS (3), not episodes (2)
        snap = om.registry().snapshot()
        assert snap["paddle_tpu_slo_breaches_total"]["series"][
            ("ttft_p95",)] == 3.0


# ---------------------------------------------------------------------------
# cross-process: two spawned replicas ship skewed latencies, the
# monitor over the aggregator attributes the breach to the slow one
# ---------------------------------------------------------------------------
def _slo_worker(endpoint, name, lat_s, n, q):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        from paddle_tpu import observability as wobs
        from paddle_tpu.observability import fleet as wfleet
        wobs.enable()
        wfleet.set_identity(process=name, role="engine")
        h = wobs.registry().histogram(
            "paddle_tpu_request_ttft_seconds", "test ttft")
        for _ in range(n):
            h.observe(lat_s)
        agent = wfleet.FleetAgent(endpoint, interval_s=60.0,
                                  timeout_s=30.0)
        ok = agent.ship()
        agent.stop()
        q.put((name, bool(ok)))
    except BaseException as e:      # report instead of hanging parent
        q.put((name, f"ERROR: {e!r}"))
        raise


class TestCrossProcessSLO:
    def test_breach_attributes_slow_process_one_bundle(self, tmp_path):
        from paddle_tpu.observability import fleet
        obs.enable()
        flight.arm(str(tmp_path))
        agg = fleet.serve_aggregator(stale_after_s=60.0)
        try:
            ctx = multiprocessing.get_context("spawn")
            q = ctx.Queue()
            ws = [ctx.Process(target=_slo_worker,
                              args=(agg.endpoint, nm, lat, 40, q))
                  for nm, lat in (("fast-rep", 0.01),
                                  ("slow-rep", 2.0))]
            for w in ws:
                w.start()
            reports = dict(q.get(timeout=180) for _ in ws)
            for w in ws:
                w.join(60)
            assert reports == {"fast-rep": True, "slow-rep": True}, \
                reports
            mon = slo_fleet.FleetSLOMonitor(agg=agg, rules=[
                slo.SLO("ttft_p95",
                        "paddle_tpu_request_ttft_seconds",
                        threshold_s=0.5, objective=0.95)])
            (res,) = mon.evaluate()
            assert not res.ok and res.count == 80
            assert res.attained == pytest.approx(0.5, abs=0.05)
            assert res.worst_process == "slow-rep"
            assert res.per_process["fast-rep"] == pytest.approx(
                1.0, abs=0.02)
            bundles = [p for p in os.listdir(str(tmp_path))
                       if p.startswith("bundle_")]
            assert len(bundles) == 1 and "slo_breach" in bundles[0]
            # idle window after the breach: no new bundle, latched
            (res2,) = mon.evaluate()
            assert res2.ok and res2.attained is None
            assert len([p for p in os.listdir(str(tmp_path))
                        if p.startswith("bundle_")]) == 1
        finally:
            agg.close()


# ---------------------------------------------------------------------------
# Autoscaler: hysteresis, journal, exactly-one-bundle-per-decision
# ---------------------------------------------------------------------------
class _ScriptedMonitor:
    """A FleetSLOMonitor stand-in whose evaluate() pops scripted
    verdicts: 'breach', 'calm' (comfortably above objective), 'ok'
    (above objective but inside the retire margin), 'idle' (vacuous)."""

    def __init__(self, script):
        self.registry = om.registry()
        self.script = list(script)
        self.rule = slo.SLO("ttft_p95",
                            "paddle_tpu_request_ttft_seconds",
                            threshold_s=0.5, objective=0.9)

    def evaluate(self):
        kind = self.script.pop(0) if self.script else "idle"
        att = {"breach": 0.4, "calm": 1.0, "ok": 0.905,
               "idle": None}[kind]
        return [slo_fleet.FleetSLOResult(
            self.rule, att, 0 if att is None else 100,
            per_process={"p0": att} if att is not None else {},
            worst_process="p0" if att is not None else None)]


class _ScriptedActuator:
    def __init__(self, n=1, refuse_grows=0):
        self.n = n
        self.log = []
        self.refuse_grows = refuse_grows

    def grow(self):
        if self.refuse_grows > 0:       # spawn still pending
            self.refuse_grows -= 1
            self.log.append("grow-refused")
            return None
        self.n += 1
        self.log.append("grow")
        return "replica-%d" % self.n

    def retire(self):
        self.n -= 1
        self.log.append("retire")
        return "replica-%d" % (self.n + 1)

    def replicas(self):
        return self.n


class TestAutoscaler:
    def test_grow_after_streak_with_trigger_and_journal(self, tmp_path):
        obs.enable()
        mon = _ScriptedMonitor(["breach"] * 4)
        act = _ScriptedActuator()
        journal = str(tmp_path / "scale.jsonl")
        asc = Autoscaler(act, mon, max_replicas=3, grow_after=3,
                         cooldown_scans=0, journal_path=journal)
        assert asc.scan() is None and asc.scan() is None
        dec = asc.scan()                # third consecutive breach
        assert dec is not None and dec["action"] == "grow"
        assert dec["replicas_before"] == 1
        assert dec["replicas_after"] == 2
        assert dec["trigger"]["slo"] == "ttft_p95"
        assert dec["trigger"]["threshold_s"] == 0.5
        assert dec["trigger"]["worst_process"] == "p0"
        assert act.log == ["grow"]
        with open(journal) as f:
            recs = [json.loads(ln) for ln in f]
        assert [r["state"] for r in recs] == ["pending", "committed"]
        assert all(r["action"] == "grow" for r in recs)
        # streak reset on commit: the 4th breach alone can't re-grow
        assert asc.scan() is None

    def test_exactly_one_bundle_per_decision_zero_on_steady(
            self, tmp_path):
        obs.enable()
        flight.arm(str(tmp_path / "flight"))
        os.makedirs(str(tmp_path / "flight"), exist_ok=True)

        def bundles():
            return [p for p in os.listdir(str(tmp_path / "flight"))
                    if p.startswith("bundle_")]

        # steady load: every scan comfortable, fleet at min — zero
        # decisions, zero bundles
        asc = Autoscaler(_ScriptedActuator(),
                         _ScriptedMonitor(["calm"] * 6),
                         retire_after=2, cooldown_scans=0)
        for _ in range(6):
            assert asc.scan() is None   # n==min_replicas: no retire
        assert bundles() == []
        assert asc.decisions == []
        # breach -> grow -> recover -> retire: exactly two bundles,
        # one per committed decision
        mon = _ScriptedMonitor(["breach", "breach"] + ["calm"] * 3)
        act = _ScriptedActuator()
        asc = Autoscaler(act, mon, grow_after=2, retire_after=3,
                         cooldown_scans=0, max_replicas=3)
        decs = [asc.scan() for _ in range(5)]
        committed = [d for d in decs if d is not None]
        assert [d["action"] for d in committed] == ["grow", "retire"]
        names = sorted(bundles())
        assert len(names) == 2
        assert all("autoscale_decision" in n for n in names)
        with open(os.path.join(str(tmp_path / "flight"), names[0],
                               "meta.json")) as f:
            meta = json.load(f)
        assert meta["detail"]["action"] == "grow"
        assert meta["detail"]["trigger"]["series"] == \
            "paddle_tpu_request_ttft_seconds"

    def test_aborted_grow_keeps_streak_and_retries(self, tmp_path):
        """The async-actuator contract: a grow that returns None
        (spawn still pending) journals an abort but must NOT reset
        the breach streak or start a cooldown — the very next scan
        retries and commits once the replica is ready."""
        obs.enable()
        mon = _ScriptedMonitor(["breach"] * 5)
        act = _ScriptedActuator(refuse_grows=2)
        journal = str(tmp_path / "scale.jsonl")
        asc = Autoscaler(act, mon, grow_after=2, cooldown_scans=2,
                         journal_path=journal)
        assert asc.scan() is None       # streak 1: observe
        assert asc.scan() is None       # streak 2: grow -> refused
        assert asc.scan() is None       # retry -> refused
        dec = asc.scan()                # retry -> committed
        assert dec is not None and dec["action"] == "grow"
        assert act.log == ["grow-refused", "grow-refused", "grow"]
        with open(journal) as f:
            states = [json.loads(ln)["state"] for ln in f]
        assert states == ["pending", "aborted", "pending", "aborted",
                          "pending", "committed"]
        # cooldown armed only by the COMMIT
        assert asc.scan() is None

    def test_ceiling_floor_and_cooldown(self):
        obs.enable()
        act = _ScriptedActuator(n=3)
        asc = Autoscaler(act, _ScriptedMonitor(["breach"] * 4),
                         max_replicas=3, grow_after=1,
                         cooldown_scans=0)
        for _ in range(4):
            assert asc.scan() is None   # at ceiling: never grows
        assert act.log == []
        act = _ScriptedActuator(n=2)
        asc = Autoscaler(act, _ScriptedMonitor(
            ["calm", "calm", "breach", "breach"]),
            min_replicas=1, max_replicas=3, grow_after=1,
            retire_after=2, cooldown_scans=2)
        assert asc.scan() is None
        dec = asc.scan()
        assert dec is not None and dec["action"] == "retire"
        # cooldown: the following breaches are observed, not acted on
        assert asc.scan() is None and asc.scan() is None
        snap = om.registry().snapshot()
        assert snap["paddle_tpu_autoscaler_replicas"]["series"][
            ()] == 1.0
        assert snap["paddle_tpu_autoscaler_decisions_total"]["series"][
            ("retire",)] == 1.0
        assert snap["paddle_tpu_autoscaler_last_decision"]["series"][
            ("retire",)] == 1.0

    def test_ok_inside_margin_is_not_calm(self):
        """Attained above objective but inside retire_margin must
        neither grow nor retire — the hysteresis dead band."""
        obs.enable()
        act = _ScriptedActuator(n=2)
        asc = Autoscaler(act, _ScriptedMonitor(["ok"] * 5),
                         retire_after=1, retire_margin=0.02,
                         cooldown_scans=0)
        for _ in range(5):
            assert asc.scan() is None
        assert act.log == []


# ---------------------------------------------------------------------------
# the router's elastic surface (what the actuator actuates)
# ---------------------------------------------------------------------------
class TestElasticRouter:
    def test_grow_serves_and_retire_drains_onto_survivors(
            self, tiny_gpt):
        obs.enable()
        router = Router(_engine_factory(tiny_gpt), n_replicas=1)
        single = LLMEngine(tiny_gpt, max_batch=2, block_size=16,
                           decode_chunk=4, prompt_quantum=16,
                           max_model_len=64)
        prompts = _prompts(4)
        want = {str(i): r.output_ids for i, r in enumerate(
            single.generate(prompts, max_new_tokens=6))}
        grown = router.add_replica()
        assert grown == "replica-1" and len(router.replicas) == 2
        assert router.stats["grown"] == 1
        for i, p in enumerate(prompts):
            router.submit(str(i), p, max_new_tokens=6)
        # retire mid-flight: victims must re-serve on the survivor
        # bit-identically (greedy decode is deterministic)
        retired = router.retire_replica(grown)
        assert retired == grown
        assert router.stats["retired"] == 1
        done = {}
        while router.has_unfinished:
            for r in router.step():
                done[r.request_id] = r
        assert len(done) == 4
        for rid, r in done.items():
            assert r.ok, (rid, r.error)
            np.testing.assert_array_equal(r.output_ids, want[rid])
        # the retired replica's state gauges read 0 (exports stop
        # naming it as live)
        snap = om.registry().snapshot()
        states = snap["paddle_tpu_router_replica_state"]["series"]
        assert states[(grown, "healthy")] == 0.0
        assert states[(grown, "dead")] == 0.0

    def test_never_retires_last_live_replica(self, tiny_gpt):
        router = Router(_engine_factory(tiny_gpt), n_replicas=1)
        assert router.retire_replica() is None
        assert len(router.replicas) == 1

    def test_engine_factory_override_attaches_preprovisioned(
            self, tiny_gpt):
        """The async-grow path: an actuator that spawned the engine
        out-of-band attaches the READY engine through the override —
        the router must use it, not the construction factory."""
        calls = []

        def counting_factory(i):
            calls.append(i)
            return _engine_factory(tiny_gpt)(i)

        router = Router(counting_factory, n_replicas=1)
        assert calls == [0]
        pre = _engine_factory(tiny_gpt)(99)
        router.add_replica(engine_factory=lambda _i, e=pre: e)
        assert calls == [0]             # construction factory unused
        assert router.replicas.handles[1].engine is pre
        done = _serve_all(router, _prompts(2), 4)
        assert all(r.ok for r in done.values())

    def test_replica_seconds_accumulates_retirees(self, tiny_gpt):
        router = Router(_engine_factory(tiny_gpt), n_replicas=2)
        time.sleep(0.05)
        before = router.replica_seconds()
        assert before >= 0.1            # 2 replicas x >=0.05s
        router.retire_replica()
        after = router.replica_seconds()
        assert after >= before
        time.sleep(0.05)
        # the retiree's clock stopped; the survivor's keeps running
        assert router.replica_seconds() - after == pytest.approx(
            0.05, abs=0.04)

    def test_retire_shuts_down_process_like_engine(self):
        stops = []

        class _FakeEngine:
            def __init__(self):
                self.has_unfinished = False

            def add_request(self, *a, **k):
                pass

            def step(self):
                return []

            def abort_request(self, rid):
                return False

            def shutdown(self):
                stops.append(True)

        router = Router(lambda i: _FakeEngine(), n_replicas=2)
        router.retire_replica()
        assert stops == [True]

    def test_concurrent_stepping_for_safe_engines(self):
        """Engines that declare concurrent_step_safe are stepped on
        pool threads (process-backed fleets overlap their compute);
        default engines keep the sequential router-thread path."""
        threads = set()

        class _Eng:
            def __init__(self, safe):
                if safe:
                    self.concurrent_step_safe = True
                self.pending = []

            @property
            def has_unfinished(self):
                return bool(self.pending)

            def add_request(self, rid, prompt, max_new, **kw):
                self.pending.append((rid, prompt))

            def step(self):
                threads.add(threading.current_thread().name)
                from paddle_tpu.inference.llm_engine import \
                    GenerationResult
                out = [GenerationResult(
                    request_id=rid, prompt_ids=p,
                    output_ids=np.zeros((2,), np.int32),
                    finish_reason="length", error=None)
                    for rid, p in self.pending]
                self.pending.clear()
                return out

            def abort_request(self, rid):
                return False

        for safe in (True, False):
            threads.clear()
            router = Router(lambda i, s=safe: _Eng(s), n_replicas=3,
                            affinity=False)
            for i, p in enumerate(_prompts(6)):
                router.submit(i, p, max_new_tokens=2)
            done = {}
            while router.has_unfinished:
                for r in router.step():
                    done[r.request_id] = r
            assert len(done) == 6 and all(r.ok for r in done.values())
            on_pool = [t for t in threads
                       if t.startswith("router-step")]
            if safe:
                assert on_pool, threads
            else:
                assert not on_pool, threads


def _serve_all(router, prompts, n_new):
    for i, p in enumerate(prompts):
        router.submit(f"g{i}", p, max_new_tokens=n_new)
    done = {}
    while router.has_unfinished:
        for r in router.step():
            done[r.request_id] = r
    return done


# ---------------------------------------------------------------------------
# traffic harness: determinism + accounting
# ---------------------------------------------------------------------------
class TestTrafficModel:
    def test_deterministic_across_instances(self):
        a = list(TrafficModel(seed=11).events(60))
        b = list(TrafficModel(seed=11).events(60))
        assert len(a) == 60
        for ea, eb in zip(a, b):
            assert ea.rid == eb.rid and ea.t == eb.t
            assert ea.cohort == eb.cohort and ea.session == eb.session
            assert ea.max_new == eb.max_new
            np.testing.assert_array_equal(ea.prompt, eb.prompt)

    def test_seeds_and_cohort_mix_differ(self):
        a = list(TrafficModel(seed=1).events(80))
        b = list(TrafficModel(seed=2).events(80))
        assert any(ea.rid != eb.rid or len(ea.prompt) != len(eb.prompt)
                   for ea, eb in zip(a, b))
        assert len({e.cohort for e in a}) >= 2   # heavy-tailed mix
        # multi-turn sessions exist: some session recurs
        sessions = [e.session for e in a if e.session is not None]
        assert len(sessions) > len(set(sessions))

    def test_run_traffic_accounting_reconciles(self, tiny_gpt):
        obs.enable()
        tm = TrafficModel(seed=5, base_rate=50.0, burst_rate=100.0,
                          max_body=40, max_out=6)
        evs = list(tm.events(24))
        router = Router(_engine_factory(tiny_gpt), n_replicas=2)
        rep = run_traffic(router, evs, time_scale=0.0, max_prompt=40)
        assert rep["submitted"] == 24
        assert rep["ok"] + rep["shed"] + rep["failed"] == 24
        assert rep["failed"] == 0
        assert rep["replica_seconds"] > 0
        per_cohort = sum(c["submitted"]
                         for c in rep["cohorts"].values())
        assert per_cohort == 24
        for c in rep["cohorts"].values():
            if c["ok"]:
                assert c["e2e_p50_s"] is not None
                assert c["e2e_p95_s"] >= c["e2e_p50_s"]


# ---------------------------------------------------------------------------
# quantiles_by_label (promoted metrics helper)
# ---------------------------------------------------------------------------
class TestQuantilesByLabel:
    def test_per_label_aggregation_and_window_delta(self):
        obs.enable()
        h = om.registry().histogram("t_qbl_seconds", "",
                                    ("op", "group"))
        for _ in range(40):
            h.labels(op="fast", group="g0").observe(0.01)
            h.labels(op="fast", group="g1").observe(0.012)
            h.labels(op="slow", group="g0").observe(1.0)
        doc = json.loads(om.registry().to_json())
        out = om.quantiles_by_label(doc, "t_qbl_seconds", "op")
        # the two fast groups merged under one label value
        assert out["fast"]["count"] == 80
        assert out["slow"]["count"] == 40
        assert out["fast"]["p95"] < 0.1 < out["slow"]["p50"]
        # windowed read: only the delta since `prev` counts
        for _ in range(10):
            h.labels(op="slow", group="g0").observe(0.01)
        doc2 = json.loads(om.registry().to_json())
        win = om.quantiles_by_label(doc2, "t_qbl_seconds", "op",
                                    prev=doc)
        assert win["slow"]["count"] == 10
        assert win["slow"]["p95"] < 0.1
        # absent metric / non-histogram: empty, not a crash
        assert om.quantiles_by_label(doc, "nope", "op") == {}


# ---------------------------------------------------------------------------
# tools: known_failures --staleness audit + obs_top slo panel
# ---------------------------------------------------------------------------
def _tools_mod(name):
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    sys.path.insert(0, tools)
    try:
        return __import__(name)
    finally:
        sys.path.remove(tools)


class TestKnownFailuresStaleness:
    def test_buckets(self, tmp_path):
        kf = _tools_mod("known_failures")
        d = tmp_path / "tests"
        d.mkdir()
        (d / "test_alive.py").write_text(
            "def test_still_failing():\n    pass\n"
            "def test_now_passing():\n    pass\n")
        manifest = {
            "failures": [
                "tests/test_alive.py::test_still_failing",
                "tests/test_alive.py::test_renamed_away",
                "tests/test_gone.py::test_anything",
            ],
            "flaky": ["tests/test_alive.py::test_now_passing[x-1]"],
        }
        out = kf.classify_staleness(
            manifest,
            failed=["tests/test_alive.py::test_still_failing"],
            root=str(tmp_path))
        assert out["file_missing"] == [
            "tests/test_gone.py::test_anything"]
        assert out["test_missing"] == [
            "tests/test_alive.py::test_renamed_away"]
        # parametrized id resolves to the bare function name
        assert out["absent_this_run"] == [
            "tests/test_alive.py::test_now_passing[x-1]"]


class TestObsTopSLOPanel:
    def test_renders_verdicts_budget_and_autoscaler(self, tiny_gpt):
        obs_top = _tools_mod("obs_top")
        obs.enable()
        # real series from the real stack: engine traffic + monitor +
        # autoscaler accounting
        eng = _engine_factory(tiny_gpt)(0)
        eng.generate(_prompts(2), max_new_tokens=4)
        mon = slo_fleet.FleetSLOMonitor(
            registry=om.registry(), flight_on_breach=False,
            rules=[slo.SLO("ttft_p95",
                           "paddle_tpu_request_ttft_seconds",
                           threshold_s=10.0, objective=0.9)])
        mon.evaluate()
        asc = Autoscaler(_ScriptedActuator(n=2),
                         _ScriptedMonitor([]), cooldown_scans=0)
        asc.scan()
        frame = obs_top.render(json.loads(obs.to_json()))
        assert "== slo ==" in frame
        assert "ttft_p95" in frame and "ok" in frame
        assert "ttft budget" in frame
        assert "prefill_compute" in frame
        assert "replicas=2" in frame

    def test_absent_without_slo_series(self):
        obs_top = _tools_mod("obs_top")
        assert "== slo ==" not in obs_top.render({})
