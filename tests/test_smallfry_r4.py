"""Round-4 small-fry API batch (VERDICT r3 missing #4 / next-7):
paddle.hub, utils.flops + summary wiring, iinfo/finfo, static.nn
control flow."""
import numpy as np
import pytest

import paddle_tpu as pt


def test_iinfo_finfo():
    ii = pt.iinfo("int8")
    assert (ii.min, ii.max, ii.bits) == (-128, 127, 8)
    fi = pt.finfo("float32")
    assert fi.bits == 32 and fi.eps == np.finfo(np.float32).eps
    bf = pt.finfo("bfloat16")
    assert bf.bits == 16 and bf.eps == 0.0078125
    with pytest.raises(Exception):
        pt.iinfo("not_a_dtype")


@pytest.fixture
def hub_repo(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        "import paddle_tpu as pt\n"
        "def tiny_mlp(width=8):\n"
        "    'A tiny MLP.'\n"
        "    return pt.nn.Linear(4, width)\n"
        "def _private():\n"
        "    pass\n")
    return str(tmp_path)


def test_hub_local(hub_repo):
    assert pt.hub.list(hub_repo, source="local") == ["tiny_mlp"]
    assert "tiny MLP" in pt.hub.help(hub_repo, "tiny_mlp",
                                     source="local")
    m = pt.hub.load(hub_repo, "tiny_mlp", source="local", width=16)
    assert list(m.weight.shape) == [4, 16]
    with pytest.raises(RuntimeError):
        pt.hub.load(hub_repo, "nope", source="local")


def test_hub_missing_hubconf(tmp_path):
    with pytest.raises(FileNotFoundError):
        pt.hub.list(str(tmp_path), source="local")


def test_flops_counts_linear_and_conv():
    lin = pt.nn.Linear(8, 4)
    n = pt.utils.flops(lin, (2, 8))
    assert n == 2 * 2 * 8 * 4
    conv = pt.nn.Conv2D(3, 6, 3, padding=1)
    n = pt.utils.flops(conv, (1, 3, 8, 8))
    assert n == 2 * (1 * 6 * 8 * 8) * 3 * 9


def test_flops_custom_ops_and_detail(capsys):
    lin = pt.nn.Linear(8, 4)
    n = pt.utils.flops(lin, (1, 8),
                       custom_ops={pt.nn.Linear: lambda l, i, o: 123},
                       print_detail=True)
    assert n == 123
    assert "Total FLOPs" in capsys.readouterr().out


def test_summary_reports_flops(capsys):
    from paddle_tpu.hapi import summary
    lin = pt.nn.Linear(8, 4)
    res = summary(lin, input_size=(1, 8))
    out = capsys.readouterr().out
    assert "Total FLOPs" in out
    assert res["total_flops"] == 2 * 8 * 4


class TestStaticNNControlFlow:
    def test_cond_eager_runs_only_taken_branch(self):
        import paddle_tpu.static as st
        hits = []

        def t():
            hits.append("t")
            return pt.to_tensor(1.0)

        def f():
            hits.append("f")
            return pt.to_tensor(2.0)

        out = st.nn.cond(pt.to_tensor(False), t, f)
        assert float(out.numpy()) == 2.0 and hits == ["f"]

    def test_cond_traced(self):
        import jax
        import paddle_tpu.static as st

        def fn(p):
            return st.nn.cond(
                pt.Tensor._wrap(p),
                lambda: pt.to_tensor(np.ones(3, np.float32)) * 2,
                lambda: pt.to_tensor(np.ones(3, np.float32)) * 5)._data

        jf = jax.jit(fn)
        np.testing.assert_allclose(np.asarray(jf(np.asarray(True))),
                                   2.0)
        np.testing.assert_allclose(np.asarray(jf(np.asarray(False))),
                                   5.0)

    def test_while_loop_eager_and_traced(self):
        import jax
        import paddle_tpu.static as st
        i, acc = st.nn.while_loop(
            lambda i, a: i < 4, lambda i, a: [i + 1, a + i],
            [pt.to_tensor(0), pt.to_tensor(0)])
        assert int(i.numpy()) == 4 and int(acc.numpy()) == 6

        def fn(x0):
            i, a = st.nn.while_loop(
                lambda i, a: i._data < 4, lambda i, a: [i + 1, a + i],
                [pt.Tensor._wrap(x0), pt.to_tensor(0)])
            return a._data

        assert int(jax.jit(fn)(np.asarray(0))) == 6

    def test_case_and_switch_case(self):
        import paddle_tpu.static as st
        out = st.nn.case([(pt.to_tensor(False), lambda: pt.to_tensor(1)),
                          (pt.to_tensor(True), lambda: pt.to_tensor(2))],
                         default=lambda: pt.to_tensor(3))
        assert int(out.numpy()) == 2
        out = st.nn.switch_case(pt.to_tensor(7), {
            1: lambda: pt.to_tensor(10), 7: lambda: pt.to_tensor(70)},
            default=lambda: pt.to_tensor(-1))
        assert int(out.numpy()) == 70
        out = st.nn.switch_case(pt.to_tensor(9), {
            1: lambda: pt.to_tensor(10)},
            default=lambda: pt.to_tensor(-1))
        assert int(out.numpy()) == -1


def test_incubate_autotune_set_config(tmp_path, monkeypatch):
    """paddle.incubate.autotune.set_config (ref: incubate/autotune.py)
    maps the kernel section onto the Pallas autotune switch."""
    import os
    import warnings
    import paddle_tpu as pt
    from paddle_tpu.kernels.pallas import autotune as pa
    pt.incubate.autotune.set_config({"kernel": {"enable": False}})
    assert not pa.enabled()
    pt.incubate.autotune.set_config({"kernel": {"enable": True}})
    assert pa.enabled()
    # JSON-file form
    p = tmp_path / "tune.json"
    p.write_text('{"kernel": {"enable": false}}')
    pt.incubate.autotune.set_config(str(p))
    assert not pa.enabled()
    pt.incubate.autotune.set_config()
    assert pa.enabled()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        pt.incubate.autotune.set_config({"dataloader": {"enable": True}})
    assert w and "no-op" in str(w[0].message)
