"""Speculative decoding for the paged serving engine
(inference/speculative.py + the LLMEngine verify path + PagedKVCache
rollback).

The load-bearing property is ORACLE EXACTNESS: greedy engine outputs
with speculative_config set must be bit-identical to speculation off
and to the dense generate() baseline — including with prefix caching
under LRU eviction pressure, under mid-generation preemption, on the
LLaMA (rope) family, and on int8 pools. Rollback must be leak-free:
rejected drafts return their pages (strict allocator validation stays
on throughout), and only fully ACCEPTED blocks ever enter the
prefix-cache hash index."""
import os
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.inference import (DraftModelProposer, DraftProposer,
                                  LLMEngine, NgramProposer, PagedKVCache,
                                  SpeculativeConfig)
from paddle_tpu.inference.speculative import accept_drafts
from paddle_tpu.models import GPTForCausalLM
from paddle_tpu.models.generation import generate
from paddle_tpu.models.gpt import gpt_tiny
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


@pytest.fixture(autouse=True)
def _obs_clean():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


@pytest.fixture(scope="module")
def tiny_gpt():
    pt.seed(0)
    return GPTForCausalLM(gpt_tiny())


@pytest.fixture(scope="module")
def tiny_llama():
    pt.seed(0)
    return LlamaForCausalLM(llama_tiny())


def _oracle(model, prompt, n_new):
    out = generate(model, pt.to_tensor(np.asarray(prompt, np.int32)[None]),
                   max_new_tokens=n_new).numpy()[0]
    return out[len(prompt):]


def _spec(k=3, **kw):
    return SpeculativeConfig(num_speculative_tokens=k, **kw)


def _engine(model, spec=None, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("block_size", 16)
    kw.setdefault("decode_chunk", 4)
    kw.setdefault("prompt_quantum", 16)
    kw.setdefault("max_model_len", 64)
    return LLMEngine(model, speculative_config=spec, **kw)


def _drain(eng):
    done = {}
    while eng.has_unfinished:
        for r in eng.step():
            done[r.request_id] = r
    return done


def _repetitive_prompt(rng, pat_len=8, reps=4):
    return np.tile(rng.integers(0, 1024, (pat_len,)).astype(np.int32),
                   reps)


class _WrongProposer(DraftProposer):
    """Adversarial drafts: always propose token ids the tiny models
    essentially never emit — every draft verifies as rejected, so each
    step exercises the full KV-rollback path."""

    def propose(self, context, k):
        return np.full((k,), 1023, np.int32)


# ---------------------------------------------------------------------------
# proposers (host-side units)
# ---------------------------------------------------------------------------
class TestNgramProposer:
    def test_matches_most_recent_continuation(self):
        p = NgramProposer(1, 3)
        ctx = np.array([1, 2, 3, 4, 1, 2, 3], np.int32)
        np.testing.assert_array_equal(p.propose(ctx, 3), [4, 1, 2])

    def test_no_match_is_empty(self):
        p = NgramProposer(2, 4)
        assert p.propose(np.arange(10, dtype=np.int32), 4).size == 0

    def test_k_clamps_and_zero_k(self):
        p = NgramProposer(1, 2)
        ctx = np.array([7, 8, 9, 7, 8, 9, 7, 8], np.int32)
        assert len(p.propose(ctx, 2)) == 2
        assert p.propose(ctx, 0).size == 0

    def test_prefers_match_with_full_continuation(self):
        """Two occurrences of the suffix bigram: when the most recent
        match's continuation would be truncated below k, the earlier
        (full-k) match wins so the drafts fill the verify window;
        when the recent match has k tokens of continuation, recency
        wins (it tracks the current phase of a repetition)."""
        p = NgramProposer(1, 2)
        ctx = np.array([5, 6, 11, 12, 13, 14, 5, 6, 1, 5, 6], np.int32)
        # k=4: the late match (pos 6) has only 3 follow-up tokens ->
        # the early match supplies the full window
        np.testing.assert_array_equal(p.propose(ctx, 4),
                                      [11, 12, 13, 14])
        # k=3 fits after the late match -> recency wins
        np.testing.assert_array_equal(p.propose(ctx, 3), [1, 5, 6])

    def test_min_n_respected(self):
        # suffix unigram matches, but min_n=2 needs a bigram match
        p = NgramProposer(2, 3)
        ctx = np.array([4, 9, 1, 4], np.int32)
        assert p.propose(ctx, 2).size == 0

    def test_validation(self):
        with pytest.raises(ValueError):
            NgramProposer(3, 2)
        with pytest.raises(ValueError):
            SpeculativeConfig(num_speculative_tokens=0)
        with pytest.raises(ValueError):
            SpeculativeConfig(proposer="draft_model").build_proposer()
        with pytest.raises(ValueError):
            SpeculativeConfig(proposer="nope").build_proposer()


class TestAcceptance:
    def test_longest_matching_prefix(self):
        assert accept_drafts([1, 2, 3], [1, 2, 3, 9]) == 3
        assert accept_drafts([1, 2, 3], [1, 9, 3, 4]) == 1
        assert accept_drafts([5], [4, 4]) == 0
        assert accept_drafts([], [7]) == 0


# ---------------------------------------------------------------------------
# oracle exactness: spec on == spec off == dense generate()
# ---------------------------------------------------------------------------
class TestSpecBitIdentity:
    def test_gpt_matches_oracle_and_spec_off(self, tiny_gpt):
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 1024, (n,)).astype(np.int32)
                   for n in (5, 9, 13)] + [_repetitive_prompt(rng)]
        n_new = 12
        on = _engine(tiny_gpt, _spec())
        off = _engine(tiny_gpt)
        res_on = on.generate(prompts, max_new_tokens=n_new)
        res_off = off.generate(prompts, max_new_tokens=n_new)
        for p, a, b in zip(prompts, res_on, res_off):
            want = _oracle(tiny_gpt, p, n_new)
            np.testing.assert_array_equal(a.output_ids, want)
            np.testing.assert_array_equal(b.output_ids, want)
            assert len(a.output_ids) == n_new     # no overshoot past
            assert a.finish_reason == "length"    # max_new from drafts
        assert on.stats["spec_steps"] > 0
        assert on.cache.available_blocks == \
            on.cache.allocator.num_blocks - 1

    def test_exact_under_prefix_cache_lru_pressure(self, tiny_gpt):
        """Speculation composes with prefix caching under a pool so
        small that parked pages MUST be LRU-evicted mid-run."""
        rng = np.random.default_rng(2)
        shared = rng.integers(0, 1024, (16,)).astype(np.int32)
        prompts = [
            np.concatenate([shared,
                            rng.integers(0, 1024, (4,)).astype(np.int32)]),
            rng.integers(0, 1024, (20,)).astype(np.int32),
            rng.integers(0, 1024, (20,)).astype(np.int32),
            np.concatenate([shared,
                            rng.integers(0, 1024, (6,)).astype(np.int32)]),
        ]
        n_new = 12
        on = _engine(tiny_gpt, _spec(), max_batch=1, block_size=8,
                     num_blocks=8)
        outs_on = []
        for i, p in enumerate(prompts):
            on.add_request(i, p, max_new_tokens=n_new)
            outs_on.append(_drain(on)[i].output_ids)
        for p, a in zip(prompts, outs_on):
            np.testing.assert_array_equal(a, _oracle(tiny_gpt, p, n_new))
        assert on.cache.available_blocks == \
            on.cache.allocator.num_blocks - 1

    def test_exact_under_preemption(self, tiny_gpt):
        """A pool too small for both sequences forces mid-generation
        preemption while speculation is committing multi-token steps;
        recompute-resume + speculation must still be oracle-exact."""
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, 1024, (n,)).astype(np.int32)
                   for n in (17, 18)]
        n_new = 20
        eng = _engine(tiny_gpt, _spec(), block_size=8, num_blocks=9)
        results = eng.generate(prompts, max_new_tokens=n_new)
        assert eng.stats["preemptions"] >= 1
        for p, r in zip(prompts, results):
            np.testing.assert_array_equal(r.output_ids,
                                          _oracle(tiny_gpt, p, n_new))
        assert eng.cache.available_blocks == \
            eng.cache.allocator.num_blocks - 1

    def test_llama_family_rope(self, tiny_llama):
        rng = np.random.default_rng(4)
        prompts = [rng.integers(0, 1024, (n,)).astype(np.int32)
                   for n in (6, 11)] + [_repetitive_prompt(rng, 6, 3)]
        eng = _engine(tiny_llama, _spec())
        for p, r in zip(prompts, eng.generate(prompts,
                                              max_new_tokens=8)):
            np.testing.assert_array_equal(r.output_ids,
                                          _oracle(tiny_llama, p, 8))

    def test_int8_pool_matches_spec_off(self, tiny_gpt):
        """int8 engines aren't comparable to the fp oracle (quantised
        cache), so the oracle is the spec-OFF int8 engine — the verify
        executable must dequantise exactly like decode does."""
        from paddle_tpu.inference import calibrate_kv_scales
        import jax.numpy as jnp
        rng = np.random.default_rng(5)
        prompts = [rng.integers(0, 1024, (n,)).astype(np.int32)
                   for n in (8,)] + [_repetitive_prompt(rng, 6, 3)]
        scales = calibrate_kv_scales(tiny_gpt, prompts[0][None])
        ref = _engine(tiny_gpt, kv_quant_scales=scales)
        on = _engine(tiny_gpt, _spec(), kv_quant_scales=scales)
        assert on.cache.key_caches[0].dtype == jnp.int8
        ref_out = [r.output_ids for r in ref.generate(prompts, 8)]
        for a, b in zip([r.output_ids
                         for r in on.generate(prompts, 8)], ref_out):
            np.testing.assert_array_equal(a, b)

    def test_sampling_refused(self, tiny_gpt):
        with pytest.raises(ValueError, match="greedy"):
            _engine(tiny_gpt, _spec(), do_sample=True)


# ---------------------------------------------------------------------------
# acceptance accounting
# ---------------------------------------------------------------------------
class TestAcceptanceCounters:
    def test_same_model_draft_accepts_everything(self, tiny_gpt):
        """Self-drafting with the TARGET model is the acceptance
        oracle: its greedy continuation IS the verify target, so every
        drafted token must be accepted (acceptance rate exactly 1.0)."""
        rng = np.random.default_rng(6)
        prompts = [rng.integers(0, 1024, (n,)).astype(np.int32)
                   for n in (5, 9)]
        eng = _engine(tiny_gpt, _spec(
            proposer=DraftModelProposer(tiny_gpt)))
        for p, r in zip(prompts, eng.generate(prompts,
                                              max_new_tokens=12)):
            np.testing.assert_array_equal(r.output_ids,
                                          _oracle(tiny_gpt, p, 12))
        st = eng.stats
        assert st["spec_drafted_tokens"] > 0
        assert st["spec_accepted_tokens"] == st["spec_drafted_tokens"]

    def test_ngram_accepts_on_repetitive_prompt_deterministically(
            self, tiny_gpt):
        """The headline self-drafting property: on a repetitive prompt
        the n-gram proposer must land accepted drafts (>0), and the
        counters are a pure function of (model, prompts) — two fresh
        engines agree exactly."""
        def run():
            rng = np.random.default_rng(7)
            prompts = [_repetitive_prompt(rng), _repetitive_prompt(rng)]
            eng = _engine(tiny_gpt, _spec())
            eng.generate(prompts, max_new_tokens=16)
            return dict(eng.stats)
        a, b = run(), run()
        assert a["spec_accepted_tokens"] > 0
        assert a["spec_steps"] > 0
        # the acceptance-criteria bar: on repetitive traffic the mean
        # accepted drafts per verify step must beat 1.0 (each step
        # then commits >2 tokens incl. the bonus)
        assert a["spec_accepted_tokens"] / a["spec_steps"] > 1.0
        for k in ("spec_steps", "spec_drafted_tokens",
                  "spec_accepted_tokens", "decode_tokens"):
            assert a[k] == b[k], (k, a[k], b[k])

    def test_metrics_spans_and_gauge(self, tiny_gpt):
        from paddle_tpu.observability import tracing
        obs.enable()
        rng = np.random.default_rng(8)
        prompts = [_repetitive_prompt(rng)]
        eng = _engine(tiny_gpt, _spec())
        eng.generate(prompts, max_new_tokens=16)
        snap = obs.snapshot()
        tok = snap["paddle_tpu_engine_spec_tokens_total"]["series"]
        accepted = tok.get(("accepted",), 0)
        rejected = tok.get(("rejected",), 0)
        st = eng.stats
        assert accepted == st["spec_accepted_tokens"] > 0
        assert accepted + rejected == st["spec_drafted_tokens"]
        gauge = snap["paddle_tpu_engine_spec_acceptance_ratio"]["series"]
        assert gauge[()] == pytest.approx(
            st["spec_accepted_tokens"] / st["spec_drafted_tokens"])
        # drafted/accepted per step ride the request's trace
        ev = [e for e in tracing.events() if e["name"] == "request.verify"]
        assert ev and all("trace_id" in e for e in ev)
        assert sum(e["args"]["drafted"] for e in ev) == \
            st["spec_drafted_tokens"]
        assert sum(e["args"]["accepted"] for e in ev) == \
            st["spec_accepted_tokens"]

    def test_obs_top_renders_acceptance_line(self, tiny_gpt):
        import json
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        try:
            import obs_top
        finally:
            sys.path.pop(0)
        obs.enable()
        rng = np.random.default_rng(9)
        eng = _engine(tiny_gpt, _spec())
        eng.generate([_repetitive_prompt(rng)], max_new_tokens=12)
        frame = obs_top.render(json.loads(obs.to_json()))
        assert "spec accept" in frame


# ---------------------------------------------------------------------------
# rollback invariants: no leaks, no partial blocks in the hash index
# ---------------------------------------------------------------------------
class TestRollbackInvariants:
    def test_truncate_releases_pages_and_guards(self):
        cache = PagedKVCache(num_layers=1, num_blocks=8, kv_heads=1,
                             block_size=4, head_dim=8, layout="token")
        cache.add_sequence("s", 10)          # 3 pages
        assert len(cache.pages("s")) == 3
        freed = cache.truncate("s", 5)       # back to 2 pages
        assert freed == 1
        assert cache.length("s") == 5
        assert len(cache.pages("s")) == 2
        assert cache.allocator.num_free == 6
        assert cache.truncate("s", 5) == 0   # idempotent at same len
        with pytest.raises(ValueError):
            cache.truncate("s", 6)           # growth is extend()'s job
        cache.free_sequence("s")
        assert cache.allocator.num_free == 8

    def test_truncate_refuses_cutting_committed_prefix(self):
        cache = PagedKVCache(num_layers=1, num_blocks=8, kv_heads=1,
                             block_size=4, head_dim=8, layout="token",
                             enable_prefix_caching=True)
        toks = np.arange(10, dtype=np.int32)
        cache.add_sequence("s", 10, tokens=toks)
        cache.commit_prefix("s", toks)       # 2 full blocks committed
        with pytest.raises(ValueError, match="committed prefix"):
            cache.truncate("s", 7)
        cache.truncate("s", 9)               # above the chain: fine
        cache.free_sequence("s")

    def test_all_rejected_drafts_leak_nothing(self, tiny_gpt):
        """Every step drafts garbage, every draft is rejected, every
        step rolls back: outputs stay oracle-exact, the strict
        allocator never sees an invalid free, and the pool is fully
        recovered."""
        rng = np.random.default_rng(10)
        prompts = [rng.integers(0, 1024, (n,)).astype(np.int32)
                   for n in (5, 9, 13)]
        n_new = 10
        eng = _engine(tiny_gpt, _spec(proposer=_WrongProposer()))
        for p, r in zip(prompts, eng.generate(prompts,
                                              max_new_tokens=n_new)):
            np.testing.assert_array_equal(r.output_ids,
                                          _oracle(tiny_gpt, p, n_new))
        st = eng.stats
        assert st["spec_drafted_tokens"] > 0
        assert st["spec_accepted_tokens"] == 0
        assert eng.cache.available_blocks == \
            eng.cache.allocator.num_blocks - 1

    def test_block_accounting_conserved_every_step(self, tiny_gpt):
        """Mid-flight invariant, checked after EVERY scheduler step:
        free + parked + leased == num_blocks (+ trash), and no
        sequence ever holds more pages than its admission-validated
        token budget allows."""
        rng = np.random.default_rng(11)
        prompts = [_repetitive_prompt(rng),
                   rng.integers(0, 1024, (9,)).astype(np.int32)]
        n_new = 12
        eng = _engine(tiny_gpt, _spec())
        bs = eng.block_size
        for i, p in enumerate(prompts):
            eng.add_request(i, p, max_new_tokens=n_new)
        while eng.has_unfinished:
            eng.step()
            nb = eng.cache.allocator.num_blocks
            leased = sum(len(v) for v in eng.cache._pages.values())
            parked = eng.cache.lru_pages
            # leased includes the trash page's registration? (no — the
            # trash page is allocator-held outside any sequence)
            assert eng.cache.allocator.num_free + parked + leased \
                == nb - 1
            for s in eng.slots:
                if s is None:
                    continue
                budget_pages = -(-s.token_budget // bs)
                assert len(eng.cache.pages(s.rid)) <= budget_pages
        assert eng.cache.available_blocks == \
            eng.cache.allocator.num_blocks - 1

    def test_rejected_blocks_never_enter_prefix_index(self, tiny_gpt):
        """Prefix-cache poisoning check: with garbage drafts rejected
        and rolled back every step, a SECOND identical request must
        hit the index (committed blocks exist) and still be
        oracle-exact — committed blocks hold only accepted KV."""
        rng = np.random.default_rng(12)
        prompt = rng.integers(0, 1024, (18,)).astype(np.int32)
        n_new = 14
        eng = _engine(tiny_gpt, _spec(proposer=_WrongProposer()),
                      max_batch=1)
        eng.add_request("a", prompt, max_new_tokens=n_new)
        out1 = _drain(eng)["a"].output_ids
        hits0 = eng.stats["prefix_cache_hit_tokens"]
        eng.add_request("b", prompt, max_new_tokens=n_new)
        out2 = _drain(eng)["b"].output_ids
        want = _oracle(tiny_gpt, prompt, n_new)
        np.testing.assert_array_equal(out1, want)
        np.testing.assert_array_equal(out2, want)
        assert eng.stats["prefix_cache_hit_tokens"] > hits0
        # structural form of the same invariant: every hash-indexed
        # page belongs to a fully committed (page-aligned) chain
        assert eng.cache.cached_pages == len(eng.cache._hash_to_page)
        assert set(eng.cache._page_hash.values()) == \
            set(eng.cache._hash_to_page.keys())


# ---------------------------------------------------------------------------
# degradation: proposer/verify failures must not take the engine down
# ---------------------------------------------------------------------------
class _ExplodingProposer(DraftProposer):
    def propose(self, context, k):
        raise RuntimeError("proposer boom")


class TestDegradation:
    def test_raising_proposer_degrades_to_plain_decode(self, tiny_gpt):
        """Drafting is best-effort: a proposer that raises costs its
        drafts (that row decodes undrafted), never the step or the
        batch — outputs stay oracle-exact."""
        rng = np.random.default_rng(20)
        prompts = [rng.integers(0, 1024, (n,)).astype(np.int32)
                   for n in (5, 9)]
        eng = _engine(tiny_gpt, _spec(proposer=_ExplodingProposer()))
        for p, r in zip(prompts, eng.generate(prompts,
                                              max_new_tokens=8)):
            np.testing.assert_array_equal(r.output_ids,
                                          _oracle(tiny_gpt, p, 8))
        assert eng.stats["spec_proposer_errors"] > 0
        assert eng.stats["spec_steps"] == 0        # nothing drafted
        assert eng.cache.available_blocks == \
            eng.cache.allocator.num_blocks - 1

    def test_verify_fault_degrades_to_chunked_step(self, tiny_gpt):
        """An injected fault inside the verify path degrades that step
        to the (isolation-hardened) chunked decode path instead of
        crashing step(); serving continues and stays oracle-exact."""
        from paddle_tpu.resilience import faults
        rng = np.random.default_rng(21)
        prompt = _repetitive_prompt(rng)
        eng = _engine(tiny_gpt, _spec())
        try:
            faults.inject("engine.verify.seq",
                          exc=RuntimeError("verify boom"), times=1)
            eng.add_request("a", prompt, max_new_tokens=12)
            out = _drain(eng)["a"]
        finally:
            faults.clear_all()
        np.testing.assert_array_equal(out.output_ids,
                                      _oracle(tiny_gpt, prompt, 12))
        assert eng.stats["spec_step_errors"] == 1
        assert eng.stats["decode_chunks"] >= 1     # the degraded step
        assert eng.stats["spec_steps"] >= 1        # later steps resume
        assert eng.cache.available_blocks == \
            eng.cache.allocator.num_blocks - 1


# ---------------------------------------------------------------------------
# scheduler accounting with speculation on
# ---------------------------------------------------------------------------
class TestSchedulerComposition:
    def test_deadline_still_enforced(self, tiny_gpt):
        eng = _engine(tiny_gpt, _spec())
        t = [0.0]
        eng._now = lambda: t[0]
        rng = np.random.default_rng(13)
        eng.add_request("slow", _repetitive_prompt(rng),
                        max_new_tokens=16, deadline_s=5.0)
        eng.step()                      # prefill + first verify
        t[0] = 10.0                     # TTL elapses mid-generation
        done = _drain(eng)
        assert done["slow"].finish_reason == "deadline"
        assert eng.cache.available_blocks == \
            eng.cache.allocator.num_blocks - 1

    def test_load_shedding_still_enforced(self, tiny_gpt):
        eng = _engine(tiny_gpt, _spec(), shed_load=True, max_waiting=1)
        rng = np.random.default_rng(14)
        for i in range(4):
            eng.add_request(i, rng.integers(0, 1024, (6,)).astype(
                np.int32), max_new_tokens=4)
        done = _drain(eng)
        reasons = {r.finish_reason for r in done.values()}
        assert "rejected" in reasons
        oks = [r for r in done.values() if r.ok]
        assert oks
