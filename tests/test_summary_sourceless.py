"""(a) TB-format SummaryWriter + VisualDL callback (VERDICT r4 next-10b;
ref: python/paddle/hapi/callbacks.py VisualDL) — events verified with
tensorboard's own reader when available, plus a framing-level check.
(b) Source-less @to_static staging (next-10a): straight-line lambdas
stage; data-dependent control flow warns up front and errors clearly."""
import glob
import os
import struct
import warnings

import numpy as np
import pytest

import paddle_tpu as pt


# -- SummaryWriter ---------------------------------------------------------
def _read_records(path):
    out = []
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return out
            (n,) = struct.unpack("<Q", header)
            f.read(4)
            out.append(f.read(n))
            f.read(4)


def test_summary_writer_scalars(tmp_path):
    from paddle_tpu.callbacks import SummaryWriter
    with SummaryWriter(str(tmp_path)) as w:
        w.add_scalar("train/loss", 0.5, step=1)
        w.add_scalar("train/loss", 0.25, step=2)
        w.add_scalar("eval/acc", np.float32(0.9), step=2)
    files = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    assert len(files) == 1
    recs = _read_records(files[0])
    assert len(recs) == 4                      # file_version + 3 scalars
    assert b"brain.Event:2" in recs[0]
    assert b"train/loss" in recs[1]

    try:
        from tensorboard.backend.event_processing.event_accumulator \
            import EventAccumulator
    except ImportError:
        return
    acc = EventAccumulator(str(tmp_path))
    acc.Reload()
    assert set(acc.Tags()["scalars"]) == {"train/loss", "eval/acc"}
    losses = acc.Scalars("train/loss")
    assert [e.step for e in losses] == [1, 2]
    np.testing.assert_allclose([e.value for e in losses], [0.5, 0.25])


def test_visualdl_callback_with_fit(tmp_path):
    from paddle_tpu.callbacks import VisualDL
    import paddle_tpu.nn as nn

    class Ds(pt.io.Dataset):
        def __getitem__(self, i):
            rng = np.random.RandomState(i)
            return (rng.standard_normal(4).astype(np.float32),
                    rng.standard_normal(1).astype(np.float32))

        def __len__(self):
            return 8

    model = pt.Model(nn.Linear(4, 1))
    opt = pt.optimizer.SGD(learning_rate=0.01,
                           parameters=model.network.parameters())
    model.prepare(opt, nn.MSELoss())
    cb = VisualDL(str(tmp_path / "run"))
    model.fit(Ds(), epochs=2, batch_size=4, verbose=0, callbacks=[cb])
    files = glob.glob(str(tmp_path / "run" / "events.out.tfevents.*"))
    assert len(files) == 1
    recs = _read_records(files[0])
    assert any(b"train/loss" in r for r in recs)


# -- source-less to_static -------------------------------------------------
def test_sourceless_straightline_stages():
    ns = {}
    exec("def f(x):\n    return x * 2 + 1\n", {"__builtins__": {}}, ns)
    with pytest.warns(UserWarning, match="unretrievable"):
        sf = pt.jit.to_static(ns["f"])
    out = sf(pt.to_tensor(np.array([1.0, 2.0], np.float32)))
    np.testing.assert_allclose(out.numpy(), [3.0, 5.0])


def test_sourceless_control_flow_reports_clearly():
    import paddle_tpu.ops as ops
    ns = {"ops": ops}
    exec("def g(x):\n"
         "    if (x.sum() > 0):\n"
         "        return x\n"
         "    return -x\n", {"ops": ops, "__builtins__": __builtins__},
         ns)
    with pytest.warns(UserWarning, match="unretrievable"):
        sf = pt.jit.to_static(ns["g"])
    with pytest.raises(RuntimeError, match="source is unretrievable"):
        sf(pt.to_tensor(np.array([1.0], np.float32)))


def test_sourced_function_does_not_warn():
    def h(x):
        return x + 1

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        sf = pt.jit.to_static(h)
    out = sf(pt.to_tensor(np.array([1.0], np.float32)))
    np.testing.assert_allclose(out.numpy(), [2.0])
