"""Paths that make billion-parameter single-chip training fit (bench.py
--config gpt1p3b): per-block remat, bf16 AdamW moments, AMP over raw
batch inputs, conv autodiff under autocast, deepcopy buffer ownership.

Ref test strategy: test/collective/fleet/ recompute + AMP payloads
(SURVEY §4)."""
import copy

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import amp
from paddle_tpu.jit import TrainStep
from paddle_tpu.models.gpt import GPTConfig, gpt_tiny
from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion
from paddle_tpu.optimizer import AdamW, Momentum
import paddle_tpu.ops as ops


def _tiny_cfg(**kw):
    return GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                     num_heads=4, max_position_embeddings=128,
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                     **kw)


class TestRecompute:
    def test_gpt_recompute_matches_plain(self):
        """config.recompute re-runs block forwards in backward — same
        loss AND same grads as the plain path."""
        ids = np.random.RandomState(0).randint(0, 512, (2, 64)).astype(
            np.int32)
        labels = np.random.RandomState(1).randint(0, 512, (2, 64)).astype(
            np.int32)
        results = []
        for rc in (False, True):
            paddle.seed(7)
            m = GPTForCausalLM(_tiny_cfg(recompute=rc))
            m.train()
            crit = GPTPretrainingCriterion()
            loss = crit(m(paddle.to_tensor(ids)), paddle.to_tensor(labels))
            loss.backward()
            g = m.gpt.layers[0].mlp.fc1.weight.grad.numpy()
            results.append((float(loss.numpy()), g))
        np.testing.assert_allclose(results[0][0], results[1][0], rtol=1e-5)
        np.testing.assert_allclose(results[0][1], results[1][1],
                                   rtol=1e-4, atol=1e-5)

    def test_recompute_under_trainstep(self):
        paddle.seed(3)
        m = GPTForCausalLM(_tiny_cfg(recompute=True))
        m.train()
        opt = AdamW(learning_rate=1e-3, parameters=m.parameters())
        crit = GPTPretrainingCriterion()

        def loss_fn(mm, ids, labels):
            with amp.auto_cast(enable=True, level="O1", dtype="bfloat16"):
                logits = mm(ids)
            return crit(logits, labels)

        step = TrainStep(m, opt, loss_fn)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 512, (2, 64)).astype(np.int32)
        labels = rng.integers(0, 512, (2, 64)).astype(np.int32)
        l0 = float(step(ids, labels).numpy())
        for _ in range(4):
            loss = step(ids, labels)
        assert float(loss.numpy()) < l0  # trains


class TestMomentDtype:
    def test_bf16_moments_dtype_and_convergence(self):
        """AdamW(moment_dtype='bfloat16') stores m/v in bf16 (half the
        optimizer-state HBM) and still optimizes."""
        paddle.seed(11)
        lin = paddle.nn.Linear(16, 4)
        opt = AdamW(learning_rate=0.05, parameters=lin.parameters(),
                    moment_dtype="bfloat16")
        rng = np.random.default_rng(2)
        x = paddle.to_tensor(rng.standard_normal((32, 16)).astype(np.float32))
        y = paddle.to_tensor(rng.standard_normal((32, 4)).astype(np.float32))
        losses = []
        for _ in range(30):
            loss = ((lin(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        st = opt._get_state(lin.weight)
        assert str(st["moment1"].dtype) == "bfloat16"
        assert str(st["moment2"].dtype) == "bfloat16"
        assert losses[-1] < 0.5 * losses[0]

    def test_bf16_moments_track_f32(self):
        """Short-horizon updates with bf16 moments stay close to f32."""
        rng = np.random.default_rng(3)
        x = rng.standard_normal((8, 8)).astype(np.float32)
        params = {}
        for mdt in (None, "bfloat16"):
            paddle.seed(5)
            lin = paddle.nn.Linear(8, 8)
            opt = AdamW(learning_rate=1e-2, parameters=lin.parameters(),
                        moment_dtype=mdt)
            xt = paddle.to_tensor(x)
            for _ in range(3):
                loss = (lin(xt) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
            params[mdt] = lin.weight.numpy()
        np.testing.assert_allclose(params[None], params["bfloat16"],
                                   rtol=2e-2, atol=2e-3)


class TestDeepcopyBuffers:
    def test_deepcopy_params_own_buffers(self):
        """Deep-copied layers (TransformerEncoder stacking) must own
        distinct device buffers — XLA rejects donating one buffer twice."""
        lin = paddle.nn.Linear(8, 8)
        lin2 = copy.deepcopy(lin)
        w1, w2 = lin.weight._data, lin2.weight._data
        if hasattr(w1, "unsafe_buffer_pointer"):
            assert (w1.unsafe_buffer_pointer()
                    != w2.unsafe_buffer_pointer())
        np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))

    def test_encoder_stack_trains_under_trainstep(self):
        """The BERT-bench shape: deep-copied encoder layers + donation."""
        from paddle_tpu.models.bert import BertConfig, BertForMaskedLM
        cfg = BertConfig(vocab_size=256, hidden_size=64, num_layers=2,
                         num_heads=2, intermediate_size=128,
                         max_position_embeddings=64,
                         hidden_dropout_prob=0.0,
                         attention_dropout_prob=0.0)
        m = BertForMaskedLM(cfg)
        m.train()
        opt = AdamW(learning_rate=1e-3, parameters=m.parameters())

        def loss_fn(mm, ids, labels):
            with amp.auto_cast(enable=True, level="O1", dtype="bfloat16"):
                loss, _ = mm(ids, labels=labels)
            return loss

        step = TrainStep(m, opt, loss_fn)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 256, (2, 32)).astype(np.int32)
        labels = np.where(rng.random((2, 32)) < 0.15, ids, -100).astype(
            np.int32)
        loss = step(ids, labels)
        assert np.isfinite(float(loss.numpy()))


class TestConvAmpTrainStep:
    def test_conv_bn_trains_under_autocast(self):
        """ResNet-bench shape: raw f32 batch arrays are cast by autocast
        inside the trace, and conv autodiff works in bf16 (no
        preferred_element_type dtype clash in the transpose rule)."""
        paddle.seed(9)
        m = paddle.nn.Sequential(
            paddle.nn.Conv2D(3, 8, 3, padding=1),
            paddle.nn.BatchNorm2D(8),
            paddle.nn.ReLU(),
            paddle.nn.Flatten(),
            paddle.nn.Linear(8 * 16 * 16, 10),
        )
        m.train()
        opt = Momentum(learning_rate=0.05, momentum=0.9,
                       parameters=m.parameters())

        def loss_fn(mm, x, y):
            with amp.auto_cast(enable=True, level="O1", dtype="bfloat16"):
                logits = mm(x)
            return ops.cross_entropy(logits, y)

        step = TrainStep(m, opt, loss_fn)
        rng = np.random.default_rng(1)
        x = rng.standard_normal((4, 3, 16, 16)).astype(np.float32)
        y = rng.integers(0, 10, (4,)).astype(np.int32)
        l0 = float(step(x, y).numpy())
        for _ in range(5):
            loss = step(x, y)
        assert float(loss.numpy()) < l0
