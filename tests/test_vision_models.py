"""Vision model zoo: forward shapes, train/eval modes, and gradient flow
(ref test style: test/legacy_test/test_vision_models.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import ops
from paddle_tpu.vision import models

# 12 model families x XLA compiles: slow tier (run with --runslow)
pytestmark = pytest.mark.slow


def _x(size=64, b=2):
    rng = np.random.default_rng(0)
    return pt.to_tensor(rng.standard_normal((b, 3, size, size))
                        .astype(np.float32))


ZOO = [
    ("densenet121", models.densenet121, 64),
    ("squeezenet1_0", models.squeezenet1_0, 64),
    ("squeezenet1_1", models.squeezenet1_1, 64),
    ("mobilenet_v1", models.mobilenet_v1, 64),
    ("mobilenet_v3_small", models.mobilenet_v3_small, 64),
    ("mobilenet_v3_large", models.mobilenet_v3_large, 64),
    ("shufflenet_v2_x1_0", models.shufflenet_v2_x1_0, 64),
    ("googlenet", models.googlenet, 64),
    ("inception_v3", models.inception_v3, 299),
]


@pytest.mark.parametrize("name,ctor,size", ZOO,
                         ids=[z[0] for z in ZOO])
def test_forward_shape(name, ctor, size):
    m = ctor(num_classes=10)
    m.eval()
    out = m(_x(size, b=2))
    assert list(out.shape) == [2, 10]
    assert np.isfinite(out.numpy()).all()


def test_densenet_train_grad_flows():
    m = models.densenet121(num_classes=4)
    m.train()
    out = m(_x(64))
    loss = ops.mean(out * out)
    loss.backward()
    grads = [p.grad for p in m.parameters() if p.grad is not None]
    assert len(grads) > 100
    assert all(np.isfinite(g.numpy()).all() for g in grads[:5])


def test_shufflenet_channels_even_split():
    m = models.shufflenet_v2_x0_5(num_classes=10)
    m.eval()
    out = m(_x(64))
    assert list(out.shape) == [2, 10]


def test_mobilenet_v3_scale():
    m = models.mobilenet_v3_small(scale=0.5, num_classes=10)
    m.eval()
    assert list(m(_x(64)).shape) == [2, 10]


def test_resnet_nhwc_matches_nchw():
    """data_format='NHWC' (the TPU-preferred layout, round-4) must be
    numerically identical to NCHW given the same weights."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.vision.models import resnet18
    pt.seed(0)
    m_nhwc = resnet18(data_format="NHWC", num_classes=10)
    m_nhwc.eval()
    m_nchw = resnet18(num_classes=10)
    m_nchw.eval()
    m_nchw.set_state_dict(m_nhwc.state_dict())
    x = np.random.default_rng(0).standard_normal(
        (2, 32, 32, 3)).astype(np.float32)
    a = m_nhwc(pt.to_tensor(x)).numpy()
    b = m_nchw(pt.to_tensor(x.transpose(0, 3, 1, 2).copy())).numpy()
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
    # training-mode BN statistics agree across layouts (looser: the
    # layouts reduce in different orders and 18 stacked normalizations
    # amplify f32 reduction-order noise to ~0.5% on the logits)
    m_nhwc.train()
    m_nchw.train()
    a = m_nhwc(pt.to_tensor(x)).numpy()
    b = m_nchw(pt.to_tensor(x.transpose(0, 3, 1, 2).copy())).numpy()
    np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-3)
