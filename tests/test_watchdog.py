"""Hang watchdog (SURVEY §5.2 comm-hang sanitizer analog)."""
import io
import sys
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.utils.watchdog import watchdog


def test_disarmed_is_noop():
    with watchdog(0, what="x") as w:
        assert w is None


def test_fires_and_dumps_stacks(capfd):
    with watchdog(0.05, what="slow region"):
        time.sleep(0.3)
    err = capfd.readouterr().err
    assert "slow region" in err and "watchdog" in err
    assert "Thread" in err or "File" in err  # faulthandler dump


def test_fast_region_stays_silent(capfd):
    with watchdog(5.0, what="quick"):
        pass
    assert "watchdog" not in capfd.readouterr().err


def test_flags_arm_trainstep(capfd):
    pt.set_flags({"FLAGS_watchdog_timeout_s": 60.0})
    try:
        model = pt.nn.Linear(4, 4)
        opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
        from paddle_tpu.jit import TrainStep
        step = TrainStep(model, opt,
                         lambda m, x: pt.ops.mean(m(x) ** 2))
        step(np.ones((2, 4), np.float32))
        assert "watchdog" not in capfd.readouterr().err
    finally:
        pt.set_flags({"FLAGS_watchdog_timeout_s": 0.0})
