# `tools` is a package so `python -m tools.graftlint` resolves from the
# repo root. The individual scripts here remain directly runnable
# (`python tools/obs_top.py`); nothing in the library imports them.
