"""Ablate the LLMEngine decode-chunk body at 1.3B: full vs no-write vs
no-attention, to locate the per-step cost over the dense fused loop.
    python tools/ablate_engine_step.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    import math
    import paddle_tpu as pt
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.inference.llm_engine import _pool_decode_attention
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.jit import _functional_params
    from paddle_tpu.autograd import tape as _tape

    cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_heads=16, max_position_embeddings=2048,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    model = GPTForCausalLM(cfg).bfloat16()
    model.eval()
    eng = LLMEngine(model, max_batch=8, num_blocks=49, block_size=64,
                    decode_chunk=16, prompt_quantum=128,
                    max_model_len=2048)
    fam, B, bs = eng.fam, 8, 64
    H_D, kvH = fam.head_dim, fam.kv_heads
    scale = 1.0 / math.sqrt(H_D)
    tensors = eng._tensors
    chunk = 16

    def make(variant):
        def decode(params, kcs, vcs, cur, lens, tbl, off, key):
            with _tape.no_grad(), _functional_params(tensors, params):
                def body(carry, _):
                    kcs, vcs, cur, lens = carry
                    x = Tensor._wrap(fam.embed(cur, lens)[:, None])
                    bidx = jnp.arange(B)
                    page = jnp.clip(lens // bs, 0, tbl.shape[1] - 1)
                    phys = jnp.maximum(tbl[bidx, page], 0)
                    flat = phys * bs + lens % bs
                    kcs2, vcs2 = [], []
                    for li, layer in enumerate(fam.layers()):
                        qkv = fam.qkv(layer, Tensor._wrap(x._data[:, 0]))
                        nH = qkv.shape[-1] // H_D - 2 * kvH
                        q = qkv[:, :nH * H_D].reshape(B, nH, H_D)
                        k = qkv[:, nH * H_D:(nH + kvH) * H_D].reshape(
                            B, kvH, H_D)
                        v = qkv[:, (nH + kvH) * H_D:].reshape(
                            B, kvH, H_D)
                        if variant == "no_write":
                            kc, vc = kcs[li], vcs[li]
                        else:
                            kc = kcs[li].at[flat].set(
                                k.astype(kcs[li].dtype))
                            vc = vcs[li].at[flat].set(
                                v.astype(vcs[li].dtype))
                        kcs2.append(kc)
                        vcs2.append(vc)
                        if variant == "no_attn":
                            rep = nH // kvH
                            o = (q + jnp.repeat(k, rep, axis=1) * 0.01
                                 ).reshape(B, nH * H_D)
                        else:
                            o = _pool_decode_attention(
                                q, kc, vc, off, lens, scale, bs)
                        x = fam.attn_out(
                            layer, x,
                            o.astype(x._data.dtype)[:, None, :])
                        x = fam.mlp(layer, x)
                    x = fam.final(x)
                    lg = fam.logits(x)._data[:, -1]
                    nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                    return (kcs2, vcs2, nxt, lens + 1), nxt

                carry = (list(kcs), list(vcs), cur, lens)
                carry, toks = jax.lax.scan(body, carry, None,
                                           length=chunk)
                return carry[0], carry[1], jnp.transpose(toks)

        return jax.jit(decode, donate_argnums=(1, 2))

    params = [t._data for t in tensors]
    NB = 49
    cur = jnp.zeros((B,), jnp.int32)
    lens = jnp.asarray(np.full((B,), 200, np.int32))
    tbln = np.full((B, eng.npb_full), eng._trash_page, np.int32)
    offn = np.full((B, NB), -1, np.int32)
    for b in range(B):
        blks = [1 + (b * 5 + j) % (NB - 1) for j in range(5)]
        tbln[b, :5] = blks
        offn[b, blks] = np.arange(5) * bs
    tblj, offj = jnp.asarray(tbln), jnp.asarray(offn)
    out = {}
    for variant in ("full", "no_write", "no_attn"):
        fn = make(variant)
        kcs = [jnp.zeros_like(a) for a in eng.cache.key_caches]
        vcs = [jnp.zeros_like(a) for a in eng.cache.value_caches]
        kcs, vcs, toks = fn(params, kcs, vcs, cur, lens, tblj, offj,
                            jax.random.PRNGKey(0))
        np.asarray(toks)
        t0 = time.perf_counter()
        for i in range(3):
            kcs, vcs, toks = fn(params, kcs, vcs, cur + i, lens, tblj,
                                offj, jax.random.PRNGKey(i))
            np.asarray(toks)
        out[variant + "_ms_per_step"] = round(
            (time.perf_counter() - t0) / 3 / chunk * 1e3, 2)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
