"""Ablate the LLMEngine decode-chunk body: full (legacy read+write of
the pool inside the scan) vs no-write vs no-attention vs staged (the
shipped side-buffer design), to locate the per-step cost over the dense
fused loop.

The "full" variant scatters into the pool AND reads it back through the
whole-pool attention in the same scan body — the aliasing pattern that
costs XLA a full pool copy per step (BENCH_EXTRA r5: ~617 MB/step at
1.3B). The "staged" variant is the engine's current body: k/v writes
land in a small [L, B, chunk] side buffer, the pool stays read-only in
the scan, and one flat token-major scatter per cache merges the chunk
at the end. Write+read is no longer superlinear when
staged_ms_per_step tracks no_write_ms_per_step instead of
full_ms_per_step.

    python tools/ablate_engine_step.py           # 1.3B (TPU box)
    python tools/ablate_engine_step.py --tiny    # CPU smoke shapes
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    import math
    import paddle_tpu as pt
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.inference.llm_engine import _pool_decode_attention
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.jit import _functional_params
    from paddle_tpu.autograd import tape as _tape

    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="smoke-size model/engine (runs on the CPU box)")
    args = ap.parse_args()

    if args.tiny:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_position_embeddings=256,
                        hidden_dropout_prob=0.0,
                        attention_dropout_prob=0.0)
        model = GPTForCausalLM(cfg)
        eng = LLMEngine(model, max_batch=2, num_blocks=24,
                        block_size=16, decode_chunk=4,
                        prompt_quantum=16, max_model_len=256)
        chunk, start_len = 4, 40
    else:
        cfg = GPTConfig(vocab_size=50304, hidden_size=2048,
                        num_layers=24, num_heads=16,
                        max_position_embeddings=2048,
                        hidden_dropout_prob=0.0,
                        attention_dropout_prob=0.0)
        model = GPTForCausalLM(cfg).bfloat16()
        eng = LLMEngine(model, max_batch=8, num_blocks=49,
                        block_size=64, decode_chunk=16,
                        prompt_quantum=128, max_model_len=2048)
        chunk, start_len = 16, 200
    model.eval()
    fam, B, bs = eng.fam, eng.max_batch, eng.block_size
    H_D, kvH = fam.head_dim, fam.kv_heads
    L = cfg.num_layers
    scale = 1.0 / math.sqrt(H_D)
    tensors = eng._tensors

    def make(variant):
        def decode(params, kcs, vcs, cur, lens, tbl, off, key):
            with _tape.no_grad(), _functional_params(tensors, params):
                cdtype = kcs[0].dtype
                st_k = jnp.zeros((L, B, chunk, kvH, H_D), cdtype)
                st_v = jnp.zeros((L, B, chunk, kvH, H_D), cdtype)
                jpos = jnp.arange(chunk, dtype=jnp.int32)

                def body(carry, i):
                    kcs_c, vcs_c, st_k, st_v, cur, lens = carry
                    x = Tensor._wrap(fam.embed(cur, lens)[:, None])
                    bidx = jnp.arange(B)
                    page = jnp.clip(lens // bs, 0, tbl.shape[1] - 1)
                    phys = jnp.maximum(tbl[bidx, page], 0)
                    flat = phys * bs + lens % bs
                    kcs2, vcs2 = [], []
                    for li, layer in enumerate(fam.layers()):
                        qkv = fam.qkv(layer, Tensor._wrap(x._data[:, 0]))
                        nH = qkv.shape[-1] // H_D - 2 * kvH
                        q = qkv[:, :nH * H_D].reshape(B, nH, H_D)
                        k = qkv[:, nH * H_D:(nH + kvH) * H_D].reshape(
                            B, kvH, H_D)
                        v = qkv[:, (nH + kvH) * H_D:].reshape(
                            B, kvH, H_D)
                        if variant == "full":
                            # legacy: pool written AND read in-body —
                            # the superlinear read+write hazard
                            kc = kcs_c[li].at[flat].set(
                                k.astype(cdtype))
                            vc = vcs_c[li].at[flat].set(
                                v.astype(cdtype))
                        else:
                            kc, vc = kcs_c[li], vcs_c[li]
                        kcs2.append(kc)
                        vcs2.append(vc)
                        if variant == "staged":
                            st_k = jax.lax.dynamic_update_slice(
                                st_k, k.astype(cdtype)[None, :, None],
                                (li, 0, i, 0, 0))
                            st_v = jax.lax.dynamic_update_slice(
                                st_v, v.astype(cdtype)[None, :, None],
                                (li, 0, i, 0, 0))
                        if variant == "no_attn":
                            rep = nH // kvH
                            o = (q + jnp.repeat(k, rep, axis=1) * 0.01
                                 ).reshape(B, nH * H_D)
                        else:
                            o = _pool_decode_attention(
                                q, kc, vc, off, lens, scale, bs)
                            if variant == "staged":
                                # the engine's staged body also attends
                                # over the side buffer; the tiny extra
                                # einsum stands in for that term
                                q4 = (q.astype(jnp.float32) * scale
                                      ).reshape(B, kvH, nH // kvH, H_D)
                                ss = jnp.einsum(
                                    "bkrd,bjkd->bkrj", q4,
                                    st_k[li].astype(jnp.float32))
                                ss = jnp.where(
                                    (jpos <= i)[None, None, None, :],
                                    ss, -jnp.inf)
                                ps = jax.nn.softmax(ss, axis=-1)
                                o = o + jnp.einsum(
                                    "bkrj,bjkd->bkrd", ps,
                                    st_v[li].astype(jnp.float32)
                                ).reshape(B, nH * H_D) * 0.0
                        x = fam.attn_out(
                            layer, x,
                            o.astype(x._data.dtype)[:, None, :])
                        x = fam.mlp(layer, x)
                    x = fam.final(x)
                    lg = fam.logits(x)._data[:, -1]
                    nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                    return (kcs2, vcs2, st_k, st_v, nxt, lens + 1), nxt

                carry = (list(kcs), list(vcs), st_k, st_v, cur, lens)
                carry, toks = jax.lax.scan(body, carry, jpos)
                kcs2, vcs2, st_k, st_v, cur, lens = carry
                if variant == "staged":
                    # merge: ONE flat token-major scatter per cache
                    gpos = (lens - chunk)[:, None] + jpos[None, :]
                    page = jnp.clip(gpos // bs, 0, tbl.shape[1] - 1)
                    phys = jnp.maximum(
                        jnp.take_along_axis(tbl, page, axis=1), 0)
                    flat = (phys * bs + gpos % bs).reshape(-1)
                    kcs2 = [kcs2[li].at[flat].set(
                        st_k[li].reshape(B * chunk, kvH, H_D))
                        for li in range(L)]
                    vcs2 = [vcs2[li].at[flat].set(
                        st_v[li].reshape(B * chunk, kvH, H_D))
                        for li in range(L)]
                return kcs2, vcs2, jnp.transpose(toks)

        return jax.jit(decode, donate_argnums=(1, 2))

    params = [t._data for t in tensors]
    NB = eng.cache.allocator.num_blocks
    cur = jnp.zeros((B,), jnp.int32)
    lens = jnp.asarray(np.full((B,), start_len, np.int32))
    tbln = np.full((B, eng.npb_full), eng._trash_page, np.int32)
    offn = np.full((B, NB), -1, np.int32)
    npages = min(5, NB - 1)
    for b in range(B):
        blks = [1 + (b * npages + j) % (NB - 1) for j in range(npages)]
        tbln[b, :npages] = blks
        offn[b, blks] = np.arange(npages) * bs
    tblj, offj = jnp.asarray(tbln), jnp.asarray(offn)
    out = {"tiny": bool(args.tiny)}
    for variant in ("full", "staged", "no_write", "no_attn"):
        fn = make(variant)
        kcs = [jnp.zeros_like(a) for a in eng.cache.key_caches]
        vcs = [jnp.zeros_like(a) for a in eng.cache.value_caches]
        kcs, vcs, toks = fn(params, kcs, vcs, cur, lens, tblj, offj,
                            jax.random.PRNGKey(0))
        np.asarray(toks)
        t0 = time.perf_counter()
        for i in range(3):
            kcs, vcs, toks = fn(params, kcs, vcs, cur + i, lens, tblj,
                                offj, jax.random.PRNGKey(i))
            np.asarray(toks)
        out[variant + "_ms_per_step"] = round(
            (time.perf_counter() - t0) / 3 / chunk * 1e3, 2)
    out["write_read_overhead_full"] = round(
        out["full_ms_per_step"] - out["no_write_ms_per_step"], 2)
    out["write_read_overhead_staged"] = round(
        out["staged_ms_per_step"] - out["no_write_ms_per_step"], 2)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
