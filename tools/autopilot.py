#!/usr/bin/env python
"""Training autopilot CLI: serve a fleet aggregator with an attached
self-healing supervisor (see README "Training autopilot").

    python tools/autopilot.py --ckpt-root runs/ckpts \\
        [--bind 127.0.0.1] [--port 0] [--flight-dir runs/flight] \\
        [--interval 1.0] [--nan-policy skip_batch|reraise_scale] \\
        [--stale-after 10] [--straggler-sustain 5] \\
        [--scale-floor-max 2] [--controller NAME] [--once]

Prints the serving endpoint (trainers point their FleetAgent AND
TrainControl at it), then runs the watch loop: each interval scans for
dead ranks / sustained stragglers, drains remediation journals, and
prints every episode as it closes. Exits non-zero with the named
AutopilotFailure when the supervisor escalates. `--once` performs a
single scan and prints status JSON (smoke/automation)."""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ckpt-root", required=True,
                    help="checkpoint directory rollbacks restore from")
    ap.add_argument("--bind", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--flight-dir", default=None,
                    help="arm the flight recorder here so every "
                         "episode dumps its autopilot_remediation "
                         "bundle")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--nan-policy", default="skip_batch",
                    choices=("skip_batch", "reraise_scale"))
    ap.add_argument("--stale-after", type=float, default=10.0)
    ap.add_argument("--straggler-sustain", type=float, default=5.0)
    ap.add_argument("--scale-floor-max", type=int, default=2)
    ap.add_argument("--controller", default=None,
                    help="process name fleet-level commands (restart/"
                         "stop) go to; default: the latest poller")
    ap.add_argument("--once", action="store_true",
                    help="one scan, print status JSON, exit")
    args = ap.parse_args(argv)

    from paddle_tpu.observability import fleet, flight
    from paddle_tpu.resilience import supervisor as sv

    if args.flight_dir:
        flight.arm(args.flight_dir, capture_faults=False,
                   min_interval_s=0.0)
    agg = fleet.serve_aggregator(
        bind=args.bind, port=args.port,
        stale_after_s=args.stale_after)
    pol = sv.Policy(nan_policy=args.nan_policy,
                    heartbeat_stale_s=args.stale_after,
                    straggler_sustain_s=args.straggler_sustain,
                    scale_floor_max=args.scale_floor_max)
    sup = sv.attach(sv.Supervisor(
        agg, ckpt_root=args.ckpt_root, policy=pol,
        controller=args.controller))
    print(f"autopilot serving at {agg.endpoint} "
          f"(ckpt_root={args.ckpt_root})", flush=True)

    if args.once:
        status = sup.scan()
        print(json.dumps({"endpoint": agg.endpoint, **status}))
        sup.close()
        agg.close()
        return 0

    seen = 0
    try:
        while True:
            sup.scan()
            done = sup.episodes(done=True)
            closed = [e for e in done if e["state"] == "done"]
            for ep in closed[seen:]:
                out = ep.get("outcome") or {}
                print(f"episode {ep['id']} [{ep['kind']}] "
                      f"process={ep['process']} -> "
                      f"{out.get('outcome', '?')} "
                      f"mttr={out.get('mttr_s', '?')}s", flush=True)
            seen = len(closed)
            if sup.failure is not None:
                print(f"AutopilotFailure: {sup.failure}",
                      file=sys.stderr, flush=True)
                return 2
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        sup.close()
        agg.close()


if __name__ == "__main__":
    sys.exit(main())
