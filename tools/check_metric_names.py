#!/usr/bin/env python
"""Static check: every `paddle_tpu_*` observability series registered
in the codebase follows the naming conventions (README "Observability")
and is documented in the README series table.

Conventions enforced:
  * every series name starts with the `paddle_tpu_` prefix
  * monotonic counters end in `_total`
  * histograms carry a base unit suffix (`_seconds` or `_bytes`)
  * gauges do NOT end in `_total` (that suffix promises monotonicity)
  * every registration carries a NON-EMPTY help string literal (the
    exposition's # HELP line is an operator's first documentation)
  * every registered name appears VERBATIM in README.md (the
    observability table lists full names, so operators can grep)

Run from the repo root (or pass it):  python tools/check_metric_names.py
Exit code 0 = clean; 1 = violations (printed one per line).
Wired into tier-1 via tests/test_prefix_cache.py so a new series can't
land undocumented or misnamed.
"""
from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Tuple

# a registration is `<registry>.counter("name", "help...", ...)` etc.
# — the name/help literals may sit on following lines (the codebase
# wraps at 72; help strings use implicit concatenation, so capturing
# the FIRST fragment is enough to prove the help is non-empty)
_REG_RE = re.compile(
    r'\.(counter|gauge|histogram)\(\s*"([A-Za-z0-9_]+)"'
    r'(?:\s*,\s*"((?:[^"\\]|\\.)*)")?')

_UNIT_SUFFIXES = ("_seconds", "_bytes")


def collect_series(root: str) -> List[Tuple[str, str, str, str]]:
    """[(kind, name, help_fragment_or_None, relpath)] for every metric
    registration under `root`/paddle_tpu (tests excluded — they
    register fixtures)."""
    found = {}
    pkg = os.path.join(root, "paddle_tpu")
    for dirpath, _, files in os.walk(pkg):
        if "__pycache__" in dirpath:
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            for kind, name, help_frag in _REG_RE.findall(text):
                key = (kind, name, os.path.relpath(path, root))
                # re.findall yields "" for a missing optional group;
                # keep the best (non-empty) help seen for the site
                found[key] = max(found.get(key, ""), help_frag,
                                 key=len)
    return sorted((k, n, h, p) for (k, n, p), h in found.items())


def check(series: List[Tuple[str, str, str, str]],
          readme_text: str) -> List[str]:
    """Returns the list of violations (empty = clean)."""
    problems = []
    for kind, name, help_frag, path in series:
        where = f"{name} ({kind}, {path})"
        if not name.startswith("paddle_tpu_"):
            problems.append(
                f"{where}: series must carry the paddle_tpu_ prefix")
            continue
        if kind == "counter" and not name.endswith("_total"):
            problems.append(
                f"{where}: counters are monotonic and must end _total")
        if kind == "gauge" and name.endswith("_total"):
            problems.append(
                f"{where}: gauges must NOT end _total (reserved for "
                "monotonic counters)")
        if kind == "histogram" and not name.endswith(_UNIT_SUFFIXES):
            problems.append(
                f"{where}: histograms must carry a base-unit suffix "
                f"({' or '.join(_UNIT_SUFFIXES)})")
        if not help_frag.strip():
            problems.append(
                f"{where}: empty or missing help string (the # HELP "
                "line is required documentation)")
        if name not in readme_text:
            problems.append(
                f"{where}: not documented in the README observability "
                "table (add the FULL series name)")
    return problems


def main(root: str = None) -> int:
    root = root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    series = collect_series(root)
    if not series:
        print("check_metric_names: found no registrations — wrong root?")
        return 1
    with open(os.path.join(root, "README.md"), encoding="utf-8") as f:
        readme = f.read()
    problems = check(series, readme)
    for p in problems:
        print(f"VIOLATION: {p}")
    if not problems:
        kinds: Dict[str, int] = {}
        for kind, _, _, _ in series:
            kinds[kind] = kinds.get(kind, 0) + 1
        detail = ", ".join(f"{v} {k}s" for k, v in sorted(kinds.items()))
        print(f"check_metric_names: {len(series)} series clean ({detail})")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
