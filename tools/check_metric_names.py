#!/usr/bin/env python
"""Thin shim over graftlint's `metric-naming` rule (the historical
entry point, kept so existing tier-1 wiring, docs and muscle memory
keep working).

The audit itself — naming conventions + README-table completeness for
every `paddle_tpu_*` series — now lives in
`tools/graftlint/rules/observability.py` alongside the span-name,
fault-point and engine.stats audits it grew into. This module
re-exports the legacy API unchanged:

  * ``collect_series(root) -> [(kind, name, help_frag, relpath)]``
  * ``check(series, readme_text) -> [violation, ...]``
  * ``main(root) -> exit code`` (prints one violation per line)

Run from the repo root (or pass it):  python tools/check_metric_names.py
Exit code 0 = clean; 1 = violations. Prefer
``python -m tools.graftlint`` for the full rule suite.
"""
from __future__ import annotations

import os
import sys
from typing import Dict

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    # the historical import path is `sys.path += ["tools"]; import
    # check_metric_names` — make the graftlint package reachable from
    # there too
    sys.path.insert(0, _ROOT)

from tools.graftlint.rules.observability import (  # noqa: E402,F401
    collect_series, check)


def main(root: str = None) -> int:
    root = root or _ROOT
    series = collect_series(root)
    if not series:
        print("check_metric_names: found no registrations — wrong root?")
        return 1
    with open(os.path.join(root, "README.md"), encoding="utf-8") as f:
        readme = f.read()
    problems = check(series, readme)
    for p in problems:
        print(f"VIOLATION: {p}")
    if not problems:
        kinds: Dict[str, int] = {}
        for kind, _, _, _ in series:
            kinds[kind] = kinds.get(kind, 0) + 1
        detail = ", ".join(f"{v} {k}s" for k, v in sorted(kinds.items()))
        print(f"check_metric_names: {len(series)} series clean ({detail})")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
