#!/usr/bin/env python
"""Static check: every `paddle_tpu_*` observability series registered
in the codebase follows the naming conventions (README "Observability")
and is documented in the README series table.

Conventions enforced:
  * every series name starts with the `paddle_tpu_` prefix
  * monotonic counters end in `_total`
  * histograms carry a base unit suffix (`_seconds` or `_bytes`)
  * gauges do NOT end in `_total` (that suffix promises monotonicity)
  * every registered name appears VERBATIM in README.md (the
    observability table lists full names, so operators can grep)

Run from the repo root (or pass it):  python tools/check_metric_names.py
Exit code 0 = clean; 1 = violations (printed one per line).
Wired into tier-1 via tests/test_prefix_cache.py so a new series can't
land undocumented or misnamed.
"""
from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Tuple

# a registration is `<registry>.counter("name", ...)` etc. — the name
# literal may sit on the following line (the codebase wraps at 72)
_REG_RE = re.compile(
    r'\.(counter|gauge|histogram)\(\s*"([A-Za-z0-9_]+)"')

_UNIT_SUFFIXES = ("_seconds", "_bytes")


def collect_series(root: str) -> List[Tuple[str, str, str]]:
    """[(kind, name, relpath)] for every metric registration under
    `root`/paddle_tpu (tests excluded — they register fixtures)."""
    found = []
    pkg = os.path.join(root, "paddle_tpu")
    for dirpath, _, files in os.walk(pkg):
        if "__pycache__" in dirpath:
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            for kind, name in _REG_RE.findall(text):
                found.append((kind, name,
                              os.path.relpath(path, root)))
    return sorted(set(found))


def check(series: List[Tuple[str, str, str]],
          readme_text: str) -> List[str]:
    """Returns the list of violations (empty = clean)."""
    problems = []
    for kind, name, path in series:
        where = f"{name} ({kind}, {path})"
        if not name.startswith("paddle_tpu_"):
            problems.append(
                f"{where}: series must carry the paddle_tpu_ prefix")
            continue
        if kind == "counter" and not name.endswith("_total"):
            problems.append(
                f"{where}: counters are monotonic and must end _total")
        if kind == "gauge" and name.endswith("_total"):
            problems.append(
                f"{where}: gauges must NOT end _total (reserved for "
                "monotonic counters)")
        if kind == "histogram" and not name.endswith(_UNIT_SUFFIXES):
            problems.append(
                f"{where}: histograms must carry a base-unit suffix "
                f"({' or '.join(_UNIT_SUFFIXES)})")
        if name not in readme_text:
            problems.append(
                f"{where}: not documented in the README observability "
                "table (add the FULL series name)")
    return problems


def main(root: str = None) -> int:
    root = root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    series = collect_series(root)
    if not series:
        print("check_metric_names: found no registrations — wrong root?")
        return 1
    with open(os.path.join(root, "README.md"), encoding="utf-8") as f:
        readme = f.read()
    problems = check(series, readme)
    for p in problems:
        print(f"VIOLATION: {p}")
    if not problems:
        kinds: Dict[str, int] = {}
        for kind, _, _ in series:
            kinds[kind] = kinds.get(kind, 0) + 1
        detail = ", ".join(f"{v} {k}s" for k, v in sorted(kinds.items()))
        print(f"check_metric_names: {len(series)} series clean ({detail})")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
