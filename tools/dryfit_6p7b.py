"""6.7B (GPT-3 class) dry-fit paths (VERDICT r4 next-1b): the
north-star config must compile and produce a measured number on this
one-chip box.

  python tools/dryfit_6p7b.py layer    # single-chip proxy on the REAL
      chip: one 6.7B transformer block + embedding/head, fwd+bwd+update
      at seq 2048, extrapolated to the 32-layer model analytically
      (prints the projected step time / MFU and each measured part)
  python tools/dryfit_6p7b.py zero3    # the FULL 6.7B model, ZeRO-3
      (p_g_os) over the virtual 8-device CPU mesh, ONE tiny-seq step —
      proves the sharded state + step compile end-to-end (slow: minutes
      of CPU time; run deliberately)

Each prints one JSON line; results recorded in BENCH_EXTRA.md.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def cmd_layer(args):
    import jax
    from paddle_tpu import amp
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.models.gpt import (GPTConfig, GPTDecoderLayer,
                                       gpt3_6p7b, num_params)
    from bench import peak_flops
    import paddle_tpu as pt
    import paddle_tpu.ops as ops

    cfg = gpt3_6p7b(hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                    use_flash_attention=True)
    b, s = args.batch, args.seq
    dev = jax.devices()[0]

    def timed_step(model, loss_fn, batch, steps=5):
        opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                    weight_decay=0.01, moment_dtype="bfloat16")
        step = TrainStep(model, opt, loss_fn)
        batch = tuple(jax.device_put(a) for a in batch)
        step(*batch)
        out = step(*batch)
        float(out.numpy())
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            for _ in range(steps):
                out = step(*batch)
            float(out.numpy())
            best = min(best, (time.perf_counter() - t0) / steps)
        return best

    rng = np.random.default_rng(0)

    # --- one decoder block, rematted like the full model would be ---
    class OneBlock(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.blk = GPTDecoderLayer(cfg)

        def forward(self, x):
            from paddle_tpu.distributed.meta_parallel.recompute import \
                recompute
            return recompute(self.blk, x)

    x = rng.standard_normal((b, s, cfg.hidden_size)).astype(np.float32)

    def blk_loss(m, x):
        with amp.auto_cast(enable=True, level="O1", dtype="bfloat16"):
            return ops.mean(m(x) ** 2)

    t_layer = timed_step(OneBlock(), blk_loss, (x,))

    # --- embedding + tied head + CE at the same shape ---
    class EmbHead(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            from paddle_tpu.models.gpt import (GPTEmbeddings,
                                               GPTPretrainingCriterion)
            self.emb = GPTEmbeddings(cfg)
            self.crit = GPTPretrainingCriterion()

        def forward(self, ids, labels):
            h = self.emb(ids)
            w = self.emb.word_embeddings.weight
            logits = ops.matmul(h, w, transpose_y=True)
            return self.crit(logits, labels)

    ids = rng.integers(0, cfg.vocab_size, (b, s)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (b, s)).astype(np.int32)

    def eh_loss(m, ids, labels):
        with amp.auto_cast(enable=True, level="O1", dtype="bfloat16"):
            return m(ids, labels)

    t_embhead = timed_step(EmbHead(), eh_loss, (ids, labels))

    proj = cfg.num_layers * t_layer + t_embhead
    n = num_params(cfg)
    tok_s = b * s / proj
    mfu = 6.0 * n * tok_s / peak_flops(dev)
    print(json.dumps({
        "mode": "layer_proxy", "config": "gpt3_6p7b",
        "batch": b, "seq": s,
        "layer_step_ms": round(t_layer * 1e3, 1),
        "embhead_step_ms": round(t_embhead * 1e3, 1),
        "projected_step_ms": round(proj * 1e3, 1),
        "projected_tokens_per_sec": round(tok_s, 1),
        "projected_mfu": round(mfu, 4),
        "note": "32*layer + embed/head measured on the real chip; "
                "inter-layer residual traffic is inside the layer "
                "timing (its input/output live in HBM)"}), flush=True)


def cmd_zero3(args):
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    from paddle_tpu import amp
    from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion
    from paddle_tpu.models.gpt import gpt3_6p7b, num_params
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.device import memory

    cfg = gpt3_6p7b(hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                    recompute=True)
    b, s = 8, args.seq
    t0 = time.perf_counter()
    pt.seed(0)
    model = GPTForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                weight_decay=0.01, moment_dtype="bfloat16")
    model, opt = dist.sharding.group_sharded_parallel(model, opt,
                                                      "p_g_os")
    t_build = time.perf_counter() - t0
    crit = GPTPretrainingCriterion()

    def loss_fn(m, ids, labels):
        with amp.auto_cast(enable=True, level="O1", dtype="bfloat16"):
            inner = getattr(m, "_layers", m)
            return crit(inner(ids), labels)

    step = TrainStep(model, opt, loss_fn)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (b, s)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (b, s)).astype(np.int32)
    t0 = time.perf_counter()
    loss = step(ids, labels)
    val = float(loss.numpy())
    t_step = time.perf_counter() - t0
    state = list(step.params) + [v for st in step.opt_states
                                 for v in st.values()]
    per_dev = memory.state_bytes_per_device(state)
    print(json.dumps({
        "mode": "zero3_dryfit", "config": "gpt3_6p7b",
        "devices": len(jax.devices()), "batch": b, "seq": s,
        "params": num_params(cfg),
        "build_s": round(t_build, 1),
        "first_step_s": round(t_step, 1),
        "loss": round(val, 4),
        "max_state_bytes_per_device_gb": round(
            max(per_dev.values()) / 1e9, 2) if per_dev else None,
    }), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("cmd", choices=["layer", "zero3"])
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=2048)
    args = ap.parse_args()
    if args.cmd == "zero3" and args.seq == 2048:
        args.seq = 64      # tiny-seq default for the CPU dry-fit
    {"layer": cmd_layer, "zero3": cmd_zero3}[args.cmd](args)


if __name__ == "__main__":
    main()
