#!/usr/bin/env python
"""Operator CLI for the persistent executable store
(`paddle_tpu.inference.exec_cache`).

    python tools/exec_cache.py <dir>                 # list entries
    python tools/exec_cache.py <dir> --verify        # integrity audit
    python tools/exec_cache.py <dir> --prune \\
        [--max-age-days N] [--max-bytes BYTES]       # evict
    python tools/exec_cache.py <dir> --json          # machine-readable

Listing shows each entry's key, compile family, payload bytes, device
fingerprint summary and age. --verify re-hashes every payload against
its manifest (the same check the engine's load path runs) and exits 1
if any entry is torn/corrupt — the store's writes are atomic
(tmp+fsync+rename, manifest last), so a bad entry means bit rot or a
foreign writer, not a crashed save. --prune drops by age then by
total-size cap (oldest first) and reaps stale staging files.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from paddle_tpu.inference.exec_cache import ExecCache  # noqa: E402


def _fmt_age(s: float) -> str:
    if s < 120:
        return "%.0fs" % s
    if s < 7200:
        return "%.0fm" % (s / 60)
    if s < 172800:
        return "%.1fh" % (s / 3600)
    return "%.1fd" % (s / 86400)


def _fmt_device(dev: dict) -> str:
    if not dev:
        return "?"
    parts = ["%s x%s" % (dev.get("device_kind", "?"),
                         dev.get("n_local_devices", "?")),
             "jax " + str(dev.get("jax", "?"))]
    if "mesh_shape" in dev:
        parts.append("mesh " + "x".join(
            str(s) for s in dev["mesh_shape"]))
    return ", ".join(parts)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="list / verify / prune a persistent executable "
                    "store directory")
    ap.add_argument("dir", help="store directory "
                    "(e.g. $PADDLE_TPU_EXEC_CACHE)")
    ap.add_argument("--verify", action="store_true",
                    help="re-hash every payload against its manifest; "
                         "exit 1 on any corrupt/torn entry")
    ap.add_argument("--prune", action="store_true",
                    help="evict entries per --max-age-days / "
                         "--max-bytes and reap stale staging files")
    ap.add_argument("--max-age-days", type=float, default=None,
                    help="with --prune: drop entries older than this")
    ap.add_argument("--max-bytes", type=int, default=None,
                    help="with --prune: evict oldest-first until the "
                         "store fits under this many payload bytes")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.dir):
        print("exec_cache: no such directory: %s" % args.dir,
              file=sys.stderr)
        return 1
    store = ExecCache(args.dir)

    if args.prune:
        max_age_s = (args.max_age_days * 86400.0
                     if args.max_age_days is not None else None)
        removed = store.prune(max_age_s=max_age_s,
                              max_bytes=args.max_bytes)
        if args.json:
            print(json.dumps({"pruned": removed}, indent=2))
        else:
            for k in removed:
                print("pruned %s" % k)
            print("exec_cache: pruned %d of %d entries"
                  % (len(removed), len(removed) + len(store.keys())))
        return 0

    recs = store.entries()
    bad = 0
    if args.verify:
        for r in recs:
            ok, why = store.verify(r["key"])
            r["ok"] = ok
            r["why"] = why
            bad += 0 if ok else 1

    if args.json:
        print(json.dumps({"root": store.root, "entries": recs},
                         indent=2, sort_keys=True))
        return 1 if bad else 0

    if not recs:
        print("exec_cache: %s is empty" % store.root)
        return 0
    total = sum(r["payload_bytes"] for r in recs)
    print("exec_cache: %d entries, %.1f MB in %s"
          % (len(recs), total / 1e6, store.root))
    for r in recs:
        line = "  %s  %-16s %9.2f MB  %-5s  %s" % (
            r["key"][:16], r["family"] or "?",
            r["payload_bytes"] / 1e6, _fmt_age(r["age_s"]),
            _fmt_device(r["device"]))
        if args.verify:
            line += "  OK" if r["ok"] else "  BAD (%s)" % r["why"]
        print(line)
    if bad:
        print("exec_cache: %d corrupt entries (run --prune or remove "
              "them; the engine load path already refuses them)" % bad)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
