"""graftlint — framework-wide static analysis encoding the repo's
TPU invariants.

Every serious regression this repo shipped was an *invariant*
violation, not a logic typo: donated buffers that outlived their call
("Array has been deleted"), host round-trips on hot dispatch paths,
mid-serving recompiles from signature drift, observability names that
silently fell out of the documented set. GSPMD / FusionStitching apply
program analysis below the framework; graftlint applies the same
discipline to the framework's own source, so those failure classes are
machine-checked before they ship.

Usage:
    python -m tools.graftlint [paths...]         # human output
    python -m tools.graftlint --json             # machine output
    python -m tools.graftlint --update-baseline  # regenerate baseline
    python -m tools.graftlint --list-rules       # registry + docs

Rule families: donation (donate-return-alias, donate-external-buffer),
purity (host-sync-in-trace, host-sync), recompile (unstable-cache-key,
unhashable-static-arg), obs (metric-naming, span-naming,
fault-point-naming, stats-key-naming). Suppress one line with
``# graftlint: disable=<rule>``; grandfathered findings live in
``tools/graftlint/baseline.json`` (new findings always fail).

graftlint is pure stdlib — it never imports jax or paddle_tpu, so it
runs instantly anywhere (tier-1 wires it through
tests/test_graftlint.py; ``bench.py --config lint`` emits
``graftlint_report.json`` for the BENCH trajectory).
"""
from .core import (                                  # noqa: F401
    Baseline, Finding, Module, Project, Report, analyze_module,
    analyze_source, build_baseline, default_baseline_path,
    iter_py_files, register, repo_root, rules, run_paths,
    write_baseline,
)
