"""CLI: ``python -m tools.graftlint [paths...]``.

Exit code 0 = zero NON-BASELINED findings (baselined ones are printed
as a count, not failures); 1 = new findings (or parse errors)."""
from __future__ import annotations

import argparse
import json
import os
import sys

from . import core


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="AST-based static analysis of the repo's TPU "
                    "invariants (donation safety, trace purity, "
                    "recompile hazards, observability discipline).")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to analyze (default: the "
                         "repo's paddle_tpu/ and tools/, resolved "
                         "against the repo root — not the cwd)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", default=core.default_baseline_path(),
                    help="baseline file (default: tools/graftlint/"
                         "baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: every finding fails")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to absorb every current "
                         "finding (carries per-entry notes forward)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run "
                         "(default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule registry and exit")
    ap.add_argument("--root", default=None,
                    help="repo root (default: derived from this file)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(core.rules().items()):
            print(f"{rid}  [{rule.family}/{rule.severity}]")
            print(f"    invariant: {rule.invariant}")
            print(f"    history:   {rule.history}")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rule_ids - set(core.rules())
        if unknown:
            print(f"graftlint: unknown rule(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2

    root = args.root or core.repo_root()
    paths = args.paths or [os.path.join(root, "paddle_tpu"),
                           os.path.join(root, "tools")]
    baseline = core.Baseline([]) if args.no_baseline else \
        core.Baseline.load(args.baseline)
    report = core.run_paths(paths, root=root,
                            rule_ids=rule_ids, baseline=baseline)
    if report.files == 0:
        # a typo'd path or wrong cwd must never read as a green gate
        print(f"graftlint: no .py files under {paths} — wrong path "
              "or cwd?", file=sys.stderr)
        return 2

    if args.update_baseline:
        notes = {rid: rule.baseline_note
                 for rid, rule in core.rules().items()
                 if getattr(rule, "baseline_note", "")}
        entries = core.build_baseline(report.findings, previous=baseline,
                                      default_notes=notes)
        core.write_baseline(args.baseline, entries)
        print(f"graftlint: baseline updated — {len(entries)} entries "
              f"covering {len(report.findings)} findings "
              f"-> {args.baseline}")
        return 0

    if args.json:
        print(json.dumps(report.to_dict(), indent=1))
    else:
        for f in report.new:
            print(f"{f.path}:{f.line}: [{f.severity}] {f.rule}: "
                  f"{f.message}")
        for path, err in report.parse_errors:
            print(f"{path}: [error] parse-error: {err}")
        pr = report.per_rule()
        detail = ", ".join(
            f"{rid}={c['new']}+{c['baselined']}b"
            for rid, c in sorted(pr.items()) if c["new"] or c["baselined"])
        print(f"graftlint: {report.files} files, "
              f"{len(report.new)} new finding(s), "
              f"{len(report.baselined)} baselined"
              + (f" ({detail})" if detail else ""))
    return 1 if (report.new or report.parse_errors) else 0


if __name__ == "__main__":
    sys.exit(main())
