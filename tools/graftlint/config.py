"""Per-path rule configuration.

Analysis-exempt paths: operator-facing CLIs whose JOB is host I/O —
profiling loops that block_until_ready around every measured window,
dashboards that print — are exempt from the host-sync inventory (the
warning-level round-trip burn-down rule). They are NOT exempt from the
error-level rules: a donation bug or a trace-impure scan body in a
profiling tool is still a bug.

The exemption list is a public contract pinned by
tests/test_graftlint.py::test_exemption_list_pinned — extending it is
a reviewed decision, not a side effect.
"""
from __future__ import annotations

from typing import FrozenSet

# path (repo-root-relative, forward slashes) -> rule ids disabled there
PATH_EXEMPTIONS = {
    # demo/profiling CLIs: measuring and rendering host-side is their
    # purpose, not a dispatch-path regression
    "tools/obs_top.py": frozenset({"host-sync"}),
    "tools/obs_dump.py": frozenset({"host-sync"}),
    "tools/profile_decode.py": frozenset({"host-sync"}),
    "tools/profile_engine.py": frozenset({"host-sync"}),
    "tools/profile_1p3b.py": frozenset({"host-sync"}),
    "tools/dryfit_6p7b.py": frozenset({"host-sync"}),
    "tools/ablate_engine_step.py": frozenset({"host-sync"}),
    "tools/resnet_traffic.py": frozenset({"host-sync"}),
    "tools/gen_ops_parity.py": frozenset({"host-sync"}),
}


# eager-dispatch hot path: the host-clock audit (purity rule
# host-clock-in-dispatch) inventories wall-clock reads ONLY under
# these prefixes — a stray perf_counter in the per-node/fused backward
# loop, the op dispatcher, or the fused optimizer step is pure
# per-dispatch overhead (ROADMAP item 4), so every site must be
# justified into the baseline. optimizer.py joined in ISSUE 13: the
# fused step is the third dispatch in the steady-state eager train
# loop (forward ops -> one whole-graph backward -> one fused step),
# so its host costs are budgeted like the backward engine's.
DISPATCH_CLOCK_AUDIT_PATHS = (
    "paddle_tpu/autograd/",
    "paddle_tpu/ops/registry.py",
    "paddle_tpu/optimizer/optimizer.py",
)


def disabled_for(path: str) -> FrozenSet[str]:
    return PATH_EXEMPTIONS.get(path, frozenset())
