"""graftlint core: the rule registry, per-file analysis context,
inline-suppression handling, the checked-in-baseline mechanism, and the
path runner the CLI / tests / bench drive.

Design:

* A **rule** is a class with an ``id``, a ``severity``, a one-line
  ``invariant`` (what must hold) and a ``history`` line (the shipped
  regression the invariant encodes). Rules are registered into a flat
  registry; the CLI can select subsets by id.
* Analysis is **AST-based and per-file** (``Module`` wraps one parsed
  source file); rules that need repo-wide context (the README tables)
  read it off the shared ``Project``. graftlint imports NOTHING from
  paddle_tpu and never imports jax — it must stay runnable in any
  environment, instantly, with ``JAX_PLATFORMS`` irrelevant.
* **Suppression** is per-line: a trailing ``# graftlint:
  disable=<rule>[,<rule>...]`` (or ``disable=all``) silences findings
  REPORTED ON exactly that physical line — one line, not a region, so
  a suppression can never silently swallow a new neighbour violation.
  Multi-line statements report on their first line; put the comment
  there.
* The **baseline** grandfathers pre-existing findings: entries are
  keyed ``(rule, path, normalized snippet)`` with an occurrence
  ``count``, so they survive unrelated line shifts but a NEW violation
  — different line content, or one more copy of the same content —
  still fails. ``--update-baseline`` regenerates the file, carrying
  forward the per-entry ``note`` justification lines.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

SEVERITIES = ("error", "warning")


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str           # repo-root-relative, forward slashes
    line: int           # 1-based
    message: str
    snippet: str        # whitespace-normalized source line

    def baseline_key(self) -> Tuple[str, str, str]:
        """Line-number-free identity: survives shifts, pins content."""
        return (self.rule, self.path, self.snippet)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _normalize(line: str) -> str:
    return " ".join(line.split())


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------
class Rule:
    """Base rule. Subclasses set the metadata and implement check()."""

    id: str = ""
    family: str = ""
    severity: str = "error"
    invariant: str = ""
    history: str = ""
    # default justification stamped on --update-baseline entries that
    # don't carry a hand-written note yet
    baseline_note: str = ""

    def check(self, mod: "Module") -> Iterable[Finding]:
        raise NotImplementedError

    # helper so rules emit uniformly
    def finding(self, mod: "Module", line: int, message: str) -> Finding:
        return Finding(self.id, self.severity, mod.path, line, message,
                       mod.snippet(line))


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator adding one rule instance to the registry."""
    if not cls.id:
        raise ValueError("rule class without id")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id!r}")
    _REGISTRY[cls.id] = cls()
    return cls


def rules() -> Dict[str, Rule]:
    """id -> rule instance, all registered rules (loads rule modules)."""
    import importlib
    # NB: must be an explicit module import — the package __init__
    # re-exports this `rules` FUNCTION, so `from . import rules` would
    # bind that attribute and never load the subpackage
    importlib.import_module(__package__ + ".rules")
    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# analysis context
# ---------------------------------------------------------------------------
_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Za-z0-9_\-, ]+)")


def _parse_suppressions(lines: List[str]) -> Dict[int, set]:
    out = {}
    for i, ln in enumerate(lines, 1):
        m = _SUPPRESS_RE.search(ln)
        if m:
            out[i] = {t.strip() for t in m.group(1).split(",") if t.strip()}
    return out


class Project:
    """Repo-wide context shared by every Module of one run."""

    def __init__(self, root: str, readme_text: Optional[str] = None):
        self.root = root
        self._readme = readme_text

    @property
    def readme(self) -> str:
        if self._readme is None:
            p = os.path.join(self.root, "README.md")
            if os.path.exists(p):
                with open(p, encoding="utf-8") as f:
                    self._readme = f.read()
            else:
                self._readme = ""
        return self._readme


class Module:
    """One parsed source file plus per-file caches rules share."""

    def __init__(self, path: str, src: str, project: Project):
        self.path = path.replace(os.sep, "/")
        self.src = src
        self.lines = src.splitlines()
        self.tree = ast.parse(src)          # SyntaxError -> caller
        self.project = project
        self.suppressed = _parse_suppressions(self.lines)
        self._parents = None
        # scratch space for cross-rule caches (scope lists, traced-
        # function sets) — see rules/_util.py mod_* helpers
        self.cache: dict = {}

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return _normalize(self.lines[line - 1])
        return ""

    @property
    def parents(self) -> dict:
        """child AST node -> parent node (built lazily, shared)."""
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def is_suppressed(self, f: Finding) -> bool:
        tags = self.suppressed.get(f.line)
        return bool(tags) and (f.rule in tags or "all" in tags)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------
class Baseline:
    """Grandfathered findings: at most `count` occurrences of each
    (rule, path, snippet) key are absorbed; everything beyond is new."""

    def __init__(self, entries: List[dict]):
        self.entries = entries
        self._allow: Dict[tuple, int] = {}
        for e in entries:
            k = (e["rule"], e["path"], e["snippet"])
            self._allow[k] = self._allow.get(k, 0) + int(e.get("count", 1))

    @classmethod
    def load(cls, path: Optional[str]) -> "Baseline":
        if not path or not os.path.exists(path):
            return cls([])
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        return cls(data.get("entries", []))

    def split(self, findings: List[Finding]
              ) -> Tuple[List[Finding], List[Finding]]:
        """findings -> (new, baselined), order preserved."""
        used: Dict[tuple, int] = {}
        new, old = [], []
        for f in findings:
            k = f.baseline_key()
            if used.get(k, 0) < self._allow.get(k, 0):
                used[k] = used.get(k, 0) + 1
                old.append(f)
            else:
                new.append(f)
        return new, old


def build_baseline(findings: List[Finding],
                   previous: Optional[Baseline] = None,
                   default_notes: Optional[Dict[str, str]] = None
                   ) -> List[dict]:
    """Entry list for the current findings. Notes survive from the
    previous baseline when the key survives; otherwise the rule's
    default justification is stamped so every entry carries a
    rule-tagged reason line."""
    prev_notes = {}
    if previous is not None:
        for e in previous.entries:
            if e.get("note"):
                prev_notes[(e["rule"], e["path"], e["snippet"])] = e["note"]
    counts: Dict[tuple, int] = {}
    order: List[tuple] = []
    for f in findings:
        k = f.baseline_key()
        if k not in counts:
            order.append(k)
        counts[k] = counts.get(k, 0) + 1
    entries = []
    for k in sorted(order):
        rule, path, snippet = k
        note = prev_notes.get(k) or (default_notes or {}).get(rule, "")
        e = {"rule": rule, "path": path, "snippet": snippet,
             "count": counts[k]}
        if note:
            e["note"] = note
        entries.append(e)
    return entries


def write_baseline(path: str, entries: List[dict]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1,
                   "comment": "graftlint grandfathered findings — burn "
                              "down by fixing a site and re-running "
                              "`python -m tools.graftlint --update-"
                              "baseline`; new findings always fail.",
                   "entries": entries}, f, indent=1, sort_keys=False,
                  ensure_ascii=False)
        f.write("\n")


# ---------------------------------------------------------------------------
# running
# ---------------------------------------------------------------------------
def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_baseline_path() -> str:
    return os.path.join(repo_root(), "tools", "graftlint",
                        "baseline.json")


def iter_py_files(paths: List[str], root: str) -> List[str]:
    """Root-relative .py paths under `paths` (files or directories)."""
    out = []
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isfile(ap):
            if ap.endswith(".py"):
                out.append(os.path.relpath(ap, root))
        else:
            for dirpath, dirnames, files in os.walk(ap):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"
                               and not d.startswith(".")]
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        out.append(os.path.relpath(
                            os.path.join(dirpath, fn), root))
    return sorted(set(p.replace(os.sep, "/") for p in out))


def analyze_module(mod: Module, rule_ids: Optional[Iterable[str]] = None
                   ) -> List[Finding]:
    """All non-suppressed findings for one Module."""
    from . import config as _config
    disabled = _config.disabled_for(mod.path)
    out = []
    for rid, rule in sorted(rules().items()):
        if rule_ids is not None and rid not in rule_ids:
            continue
        if rid in disabled:
            continue
        for f in rule.check(mod):
            if not mod.is_suppressed(f):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def analyze_source(src: str, path: str = "fixture.py",
                   rule_ids: Optional[Iterable[str]] = None,
                   readme_text: str = "",
                   root: Optional[str] = None) -> List[Finding]:
    """Analyze one in-memory source blob (the fixture/test entry)."""
    project = Project(root or repo_root(), readme_text=readme_text)
    mod = Module(path, src, project)
    return analyze_module(mod, rule_ids)


@dataclasses.dataclass
class Report:
    findings: List[Finding]          # every finding, sorted
    new: List[Finding]               # not covered by the baseline
    baselined: List[Finding]
    files: int
    parse_errors: List[Tuple[str, str]]

    def per_rule(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for bucket, fs in (("new", self.new), ("baselined", self.baselined)):
            for f in fs:
                r = out.setdefault(f.rule, {"new": 0, "baselined": 0})
                r[bucket] += 1
        return out

    def to_dict(self) -> dict:
        base_keys = {id(f) for f in self.baselined}
        findings = []
        for f in self.findings:
            d = f.to_dict()
            d["baselined"] = id(f) in base_keys
            findings.append(d)
        return {
            "findings": findings,
            "counts": {"total": len(self.findings),
                       "new": len(self.new),
                       "baselined": len(self.baselined),
                       "per_rule": self.per_rule()},
            "files": self.files,
            "parse_errors": [{"path": p, "error": e}
                             for p, e in self.parse_errors],
        }


def run_paths(paths: List[str], root: Optional[str] = None,
              rule_ids: Optional[Iterable[str]] = None,
              baseline: Optional[Baseline] = None,
              readme_text: Optional[str] = None) -> Report:
    """Analyze every .py file under `paths`; split against `baseline`."""
    root = root or repo_root()
    project = Project(root, readme_text=readme_text)
    findings: List[Finding] = []
    parse_errors: List[Tuple[str, str]] = []
    files = iter_py_files(paths, root)
    for rel in files:
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            src = f.read()
        try:
            mod = Module(rel, src, project)
        except SyntaxError as e:
            parse_errors.append((rel, str(e)))
            continue
        findings.extend(analyze_module(mod, rule_ids))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    baseline = baseline or Baseline([])
    new, old = baseline.split(findings)
    return Report(findings=findings, new=new, baselined=old,
                  files=len(files), parse_errors=parse_errors)
