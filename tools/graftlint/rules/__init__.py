"""Rule modules — importing this package registers every rule."""
from . import donation       # noqa: F401
from . import purity         # noqa: F401
from . import recompile      # noqa: F401
from . import observability  # noqa: F401
