"""Shared AST machinery for graftlint rules: dotted-name resolution,
per-scope function indexing, donated/jitted call-site discovery, and a
small flow-sensitive may-alias ("taint") evaluator.

The taint model (deliberately simple, tuned for jax framework code):

* values flow through names, tuple/list packing, ternaries, subscripts,
  attribute access and ``list.append``/``extend``;
* **calls and operators produce fresh values** — in jax, every op
  returns a new buffer (``x.at[i].set(v)``, ``lax.scan`` carries), and
  accessor calls are presumed to copy or own what they return (the
  ``state_dict()``-copies contract). Rebinding a name to a call result
  therefore CLEARS its taint — this is what makes the canonical
  "donate the input, return the successor" pattern analyze clean;
* ``if``/``else`` branches analyze on forked environments merged with
  may-alias OR; loop bodies run twice to catch loop-carried aliases.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional


def dotted(node) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def unparse(node) -> str:
    try:
        return ast.unparse(node)
    except Exception:               # pragma: no cover - defensive
        return "<expr>"


def const_int_seq(node) -> Optional[List[int]]:
    """Literal int / tuple-or-list of ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                out.append(e.value)
            else:
                return None
        return out
    return None


def keyword(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


# ---------------------------------------------------------------------------
# scopes
# ---------------------------------------------------------------------------
def own_body_nodes(fn) -> List[ast.AST]:
    """Every node in `fn`'s body EXCLUDING nested function/class
    bodies (those are separate scopes; the def node itself is
    included). The skip check runs at POP time so a def reached any
    way — initial body statement or nested child — never expands."""
    out = []
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


def scopes(tree) -> List[ast.AST]:
    """The module plus every (nested) function definition."""
    out = [tree]
    out.extend(n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)))
    return out


def local_defs(scope) -> Dict[str, ast.AST]:
    """name -> FunctionDef for defs appearing directly in `scope`'s
    body blocks (one level: module-level defs, or a function's own
    nested defs)."""
    out = {}
    body = scope.body if hasattr(scope, "body") else []
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
            continue                      # don't descend into it
        if isinstance(node, ast.ClassDef):
            continue
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                stack.append(child)
    return out


def resolve_function(name: str, scope, mod_tree) -> Optional[ast.AST]:
    """Nearest def named `name`: the current scope's nested defs first,
    then module level."""
    hit = local_defs(scope).get(name)
    if hit is not None:
        return hit
    return local_defs(mod_tree).get(name)


# -- per-Module caches (rules share one Module instance per file; the
#    raw helpers above recompute per call, which is quadratic across
#    rules x scopes on big modules) -------------------------------------
def mod_scopes(mod) -> List[ast.AST]:
    hit = mod.cache.get("scopes")
    if hit is None:
        hit = mod.cache["scopes"] = scopes(mod.tree)
    return hit


def mod_own_body(mod, scope) -> List[ast.AST]:
    # id() keying is sound here: scope nodes live exactly as long as
    # mod.tree pins them, and the cache dies with the Module
    cache = mod.cache.setdefault("own_body", {})
    hit = cache.get(id(scope))  # graftlint: disable=unstable-cache-key
    if hit is None:
        hit = cache[id(scope)] = own_body_nodes(scope)  # graftlint: disable=unstable-cache-key
    return hit


def mod_local_defs(mod, scope) -> Dict[str, ast.AST]:
    cache = mod.cache.setdefault("local_defs", {})
    hit = cache.get(id(scope))  # graftlint: disable=unstable-cache-key
    if hit is None:
        hit = cache[id(scope)] = local_defs(scope)  # graftlint: disable=unstable-cache-key
    return hit


def mod_resolve_function(mod, name, scope) -> Optional[ast.AST]:
    hit = mod_local_defs(mod, scope).get(name)
    if hit is not None:
        return hit
    return mod_local_defs(mod, mod.tree).get(name)


def param_names(fn) -> List[str]:
    args = getattr(fn, "args", None)
    if args is None:
        return []
    return [a.arg for a in list(args.posonlyargs) + list(args.args)]


# ---------------------------------------------------------------------------
# taint evaluation
# ---------------------------------------------------------------------------
class Taint:
    """Environment: name -> reason-string (tainted) or absent (clean)."""

    def __init__(self, sources=None):
        self.env: Dict[str, str] = {}
        # sources: callable(node) -> Optional[str] marking extra taint
        # origins (e.g. `x._data` attribute reads)
        self.sources = sources or (lambda node: None)

    def why(self, node) -> Optional[str]:
        """Reason `node` may alias a tainted value, else None."""
        src = self.sources(node)
        if src is not None:
            return src
        if isinstance(node, ast.Name):
            return self.env.get(node.id)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for e in node.elts:
                w = self.why(e)
                if w:
                    return w
            return None
        if isinstance(node, ast.Starred):
            return self.why(node.value)
        if isinstance(node, ast.IfExp):
            return self.why(node.body) or self.why(node.orelse)
        if isinstance(node, ast.Subscript):
            return self.why(node.value)
        if isinstance(node, ast.Attribute):
            return self.why(node.value)
        if isinstance(node, ast.NamedExpr):
            return self.why(node.value)
        # Call / BinOp / comprehension / literal: a fresh value
        return None

    # -- statement walking ------------------------------------------------
    def _assign(self, target, value_node, why: Optional[str]):
        if isinstance(target, ast.Name):
            if why:
                self.env[target.id] = why
            else:
                self.env.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(value_node, (ast.Tuple, ast.List)) and \
                    len(value_node.elts) == len(target.elts):
                for t, v in zip(target.elts, value_node.elts):
                    self._assign(t, v, self.why(v))
            else:
                for t in target.elts:
                    self._assign(t, None, why)
        elif isinstance(target, ast.Starred):
            self._assign(target.value, None, why)
        elif isinstance(target, ast.Subscript) and why:
            # storing a tainted value INTO a container taints it
            base = target.value
            if isinstance(base, ast.Name):
                self.env[base.id] = why

    def walk(self, stmts, on_stmt=None):
        """Linear flow-sensitive walk. `on_stmt(stmt, taint)` fires for
        every statement BEFORE its env effects apply (so a call site
        inside it sees the env state on entry to the statement)."""
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue                  # separate scope
            if on_stmt is not None:
                on_stmt(st, self)
            if isinstance(st, ast.Assign):
                w = self.why(st.value)
                for tgt in st.targets:
                    self._assign(tgt, st.value, w)
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                self._assign(st.target, st.value, self.why(st.value))
            elif isinstance(st, ast.AugAssign):
                if isinstance(st.target, ast.Name):
                    w = self.env.get(st.target.id) or self.why(st.value)
                    if w:
                        self.env[st.target.id] = w
            elif isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
                c = st.value
                if isinstance(c.func, ast.Attribute) and \
                        c.func.attr in ("append", "extend", "insert",
                                        "add") and \
                        isinstance(c.func.value, ast.Name):
                    for a in c.args:
                        w = self.why(a)
                        if w:
                            self.env[c.func.value.id] = w
                            break
            elif isinstance(st, ast.If):
                a = self._fork()
                a.walk(st.body, on_stmt)
                b = self._fork()
                b.walk(st.orelse, on_stmt)
                self._merge(a, b)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                self._assign(st.target, None, self.why(st.iter))
                for _ in range(2):        # catch loop-carried aliases
                    self.walk(st.body, on_stmt)
                self.walk(st.orelse, on_stmt)
            elif isinstance(st, ast.While):
                for _ in range(2):
                    self.walk(st.body, on_stmt)
                self.walk(st.orelse, on_stmt)
            elif isinstance(st, ast.With):
                for item in st.items:
                    if item.optional_vars is not None:
                        self._assign(item.optional_vars, None,
                                     self.why(item.context_expr))
                self.walk(st.body, on_stmt)
            elif isinstance(st, ast.Try):
                self.walk(st.body, on_stmt)
                for h in st.handlers:
                    self.walk(h.body, on_stmt)
                self.walk(st.orelse, on_stmt)
                self.walk(st.finalbody, on_stmt)

    def _fork(self) -> "Taint":
        t = Taint(self.sources)
        t.env = dict(self.env)
        return t

    def _merge(self, a: "Taint", b: "Taint"):
        merged = {}
        for env in (a.env, b.env):
            merged.update(env)
        self.env = merged


# ---------------------------------------------------------------------------
# jit-with-donation site discovery
# ---------------------------------------------------------------------------
def is_jit_call(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = dotted(node.func)
    return d in ("jax.jit", "jit") or (d or "").endswith(".jit")


def donated_argnums(node) -> Optional[List[int]]:
    """Literal donate_argnums of a jit call; None when absent or not
    statically resolvable."""
    kw = keyword(node, "donate_argnums")
    if kw is None:
        return None
    return const_int_seq(kw)


def call_arg_vector(mod, jit_call, scope):
    """The positional-argument vector the donated executable is invoked
    with, resolved within `scope`:

    1. AOT:    jax.jit(f, ...).lower(a, b, ...)      -> lower's args
    2. inline: jax.jit(f, ...)(a, b, ...)            -> that call's args
    3. named:  g = jax.jit(f, ...)   ...   g(a, b)   -> first g(...) call

    Returns (args, call_node) or (None, None)."""
    parents = mod.parents
    p = parents.get(jit_call)
    if isinstance(p, ast.Attribute) and p.attr == "lower":
        pp = parents.get(p)
        if isinstance(pp, ast.Call) and pp.func is p:
            return list(pp.args), pp
    if isinstance(p, ast.Call) and p.func is jit_call:
        return list(p.args), p
    # named: jit call assigned (possibly through .lower(...).compile())
    # to a simple name, then invoked in the same scope
    node, par = jit_call, p
    while isinstance(par, (ast.Attribute, ast.Call)):
        node, par = par, parents.get(par)
    if isinstance(par, ast.Assign) and len(par.targets) == 1 and \
            isinstance(par.targets[0], ast.Name):
        gname = par.targets[0].id
        for n in own_body_nodes(scope):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id == gname:
                return list(n.args), n
    return None, None
