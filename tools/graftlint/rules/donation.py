"""Donation-safety rules (family: donation).

The invariant this family encodes: ``jax.jit(...,
donate_argnums=...)`` tells XLA it may destroy the donated input
buffers at call time. That is only sound when the caller's side of the
contract holds — nothing outside the call may still reach the donated
buffer. The repo shipped exactly this bug once (VERDICT r5 Weak #1):
the fused optimizer donated parameter/gradient buffers that wrapper
optimizers (LookAhead slow weights, ModelAverage sums) legitimately
held across steps, and the failure surfaced much later as an unrelated
"Array has been deleted". The fix (donate ONLY optimizer-owned
accumulators, ``donate_argnums=(3,)``) is this family's negative test.

Two statically checkable sides of the contract:

* ``donate-return-alias`` — inside the jitted function, a donated
  parameter must not escape through ``return`` or onto an object
  attribute. Rebinding through a call (``caches = f(...)``,
  ``x.at[i].set(v)``) is the sanctioned pattern and analyzes clean.
* ``donate-external-buffer`` — at the call site, the value bound to a
  donated position must not alias an externally visible buffer: a
  framework ``Tensor``'s ``._data`` or a bare ``self.<attr>`` read.
  Values produced by CALLS are presumed owned/copied (accessors follow
  the ``state_dict()``-copies contract), which is precisely why
  ``states.append(self._get_state(p))`` is clean and
  ``work.append(p._data)`` is not.
"""
from __future__ import annotations

import ast

from ..core import Rule, register
from . import _util as U


def _donation_sites(mod, scope):
    """(jit_call, fn_node, donated_positions) for every jit call with a
    literal donate_argnums directly in `scope`."""
    out = []
    for node in U.mod_own_body(mod, scope):
        if not U.is_jit_call(node):
            continue
        nums = U.donated_argnums(node)
        if not nums or not node.args:
            continue
        fn_arg = node.args[0]
        fn = None
        if isinstance(fn_arg, ast.Name):
            fn = U.resolve_function(fn_arg.id, scope, mod.tree)
        elif isinstance(fn_arg, ast.Lambda):
            fn = fn_arg
        out.append((node, fn, sorted(set(nums))))
    return out


@register
class DonateReturnAlias(Rule):
    id = "donate-return-alias"
    family = "donation"
    severity = "error"
    invariant = ("a jitted function must not return (or store on an "
                 "object) a value aliasing a donated parameter — the "
                 "donated buffer is deleted at call time")
    history = ("fused-optimizer donation bug: donated buffers outliving "
               "the call died later as 'Array has been deleted' "
               "(VERDICT r5 Weak #1)")

    def check(self, mod):
        for scope in U.mod_scopes(mod):
            for jit_call, fn, nums in _donation_sites(mod, scope):
                if fn is None:
                    continue
                names = U.param_names(fn)
                donated = {names[i]: i for i in nums if i < len(names)}
                if not donated:
                    continue
                if isinstance(fn, ast.Lambda):
                    t = U.Taint()
                    for n in donated:
                        t.env[n] = f"donated parameter '{n}'"
                    why = t.why(fn.body)
                    if why:
                        yield self.finding(
                            mod, fn.lineno,
                            f"jitted lambda returns {why} "
                            f"(donate_argnums={sorted(donated.values())})"
                            " — the donated buffer is deleted by XLA at"
                            " call time, so the returned alias dies "
                            "with it")
                    continue
                yield from self._check_def(mod, fn, donated)

    def _check_def(self, mod, fn, donated):
        t = U.Taint()
        for n in donated:
            t.env[n] = f"donated parameter '{n}'"
        findings = []

        def on_stmt(st, taint):
            if isinstance(st, ast.Return) and st.value is not None:
                why = taint.why(st.value)
                if why:
                    findings.append(self.finding(
                        mod, st.lineno,
                        f"jitted function '{fn.name}' returns a value "
                        f"that may alias {why} — donated buffers are "
                        "deleted at call time; return the computed "
                        "successor (rebinding through an op/call) "
                        "instead of the donated input"))
            elif isinstance(st, ast.Assign):
                for tgt in st.targets:
                    if isinstance(tgt, ast.Attribute):
                        why = taint.why(st.value)
                        if why:
                            findings.append(self.finding(
                                mod, st.lineno,
                                f"jitted function '{fn.name}' stores "
                                f"{why} onto attribute "
                                f"'{U.unparse(tgt)}' — the alias "
                                "outlives the call and dies with the "
                                "donated buffer"))

        t.walk(fn.body, on_stmt)
        yield from findings


def _external_sources(node):
    """Taint origin: externally visible buffer reads.

    * ``<x>._data`` — a framework Tensor's public buffer: user code,
      wrapper optimizers and callbacks legitimately capture it.
    * bare ``self.<attr>`` reads — object state someone else can read
      later; pass a copy or an owned value to a donated position.
    Call results are NOT sources (owned-by-contract)."""
    if isinstance(node, ast.Attribute):
        if node.attr == "_data":
            return f"externally visible buffer '{U.unparse(node)}'"
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            return f"object state '{U.unparse(node)}'"
    return None


@register
class DonateExternalBuffer(Rule):
    id = "donate-external-buffer"
    family = "donation"
    severity = "error"
    invariant = ("a donated call-site argument must not alias an "
                 "externally visible buffer (Tensor._data, bare "
                 "self.<attr> state) — donate only buffers the callee's"
                 " owner exclusively holds")
    history = ("re-adding donate_argnums=(1, 3) to the fused optimizer "
               "step (donating params/grads built from p._data) "
               "reintroduces the LookAhead/ModelAverage 'Array has "
               "been deleted' regression")

    def check(self, mod):
        for scope in U.mod_scopes(mod):
            for jit_call, fn, nums in _donation_sites(mod, scope):
                args, call = U.call_arg_vector(mod, jit_call, scope)
                if args is None:
                    continue
                findings = []
                target = {}          # arg node -> donated position
                for i in nums:
                    if i < len(args):
                        target[id(args[i])] = (args[i], i)
                if not target:
                    continue

                def on_stmt(st, taint, _target=target, _call=call,
                            _findings=findings):
                    hit = any(n is _call for n in ast.walk(st))
                    if not hit:
                        return
                    for arg, pos in _target.values():
                        why = taint.why(arg)
                        if why:
                            _findings.append(self.finding(
                                mod, arg.lineno,
                                f"donated argument {pos} "
                                f"('{U.unparse(arg)}') is built from "
                                f"{why} — XLA deletes it at call time "
                                "while outside references stay live "
                                "('Array has been deleted' class); "
                                "donate only owned buffers, or copy"))
                    _target.clear()   # report once per site

                t = U.Taint(_external_sources)
                t.walk(scope.body, on_stmt)
                yield from findings
