"""Observability-discipline rules (family: obs).

The invariant: every observable NAME the runtime emits — metric
series, trace span names, resilience fault points, ``engine.stats``
keys — is part of the operator interface and must (a) follow the
naming conventions and (b) appear VERBATIM in the README tables, so an
operator can grep any name a dashboard shows straight to its
documentation. ``tools/check_metric_names.py`` pioneered this for
metric series (tier-1-wired since PR 3); this family absorbs it into
the rule registry and extends the same audit to spans, fault points
and stats keys. The old CLI remains as a thin shim importing the
legacy ``collect_series``/``check`` API from here.

Conventions enforced for metrics (unchanged from the legacy tool):
  * every series name starts with the ``paddle_tpu_`` prefix
  * monotonic counters end in ``_total``
  * histograms carry a base unit suffix (``_seconds``, ``_bytes``, or
    ``_size`` for dimensionless item counts — the Prometheus
    convention for e.g. batch sizes)
  * gauges do NOT end in ``_total`` (that suffix promises monotonicity)
  * every registration carries a NON-EMPTY help string literal
  * every registered name appears VERBATIM in README.md
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from ..core import Rule, register
from . import _util as U

_UNIT_SUFFIXES = ("_seconds", "_bytes", "_size")

# ---------------------------------------------------------------------------
# legacy API (tools/check_metric_names.py shim imports these verbatim)
# ---------------------------------------------------------------------------
# a registration is `<registry>.counter("name", "help...", ...)` etc.
# — the name/help literals may sit on following lines (the codebase
# wraps at 72; help strings use implicit concatenation, so capturing
# the FIRST fragment is enough to prove the help is non-empty)
_REG_RE = re.compile(
    r'\.(counter|gauge|histogram)\(\s*"([A-Za-z0-9_]+)"'
    r'(?:\s*,\s*"((?:[^"\\]|\\.)*)")?')


def collect_series(root: str) -> List[Tuple[str, str, str, str]]:
    """[(kind, name, help_fragment, relpath)] for every metric
    registration under `root`/paddle_tpu (tests excluded — they
    register fixtures)."""
    found = {}
    pkg = os.path.join(root, "paddle_tpu")
    for dirpath, _, files in os.walk(pkg):
        if "__pycache__" in dirpath:
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            for kind, name, help_frag in _REG_RE.findall(text):
                key = (kind, name, os.path.relpath(path, root))
                # re.findall yields "" for a missing optional group;
                # keep the best (non-empty) help seen for the site
                found[key] = max(found.get(key, ""), help_frag,
                                 key=len)
    return sorted((k, n, h, p) for (k, n, p), h in found.items())


def _series_problems(kind: str, name: str, help_frag: str,
                     where: str, readme_text: str) -> List[str]:
    problems = []
    if not name.startswith("paddle_tpu_"):
        problems.append(
            f"{where}: series must carry the paddle_tpu_ prefix")
        return problems
    if kind == "counter" and not name.endswith("_total"):
        problems.append(
            f"{where}: counters are monotonic and must end _total")
    if kind == "gauge" and name.endswith("_total"):
        problems.append(
            f"{where}: gauges must NOT end _total (reserved for "
            "monotonic counters)")
    if kind == "histogram" and not name.endswith(_UNIT_SUFFIXES):
        problems.append(
            f"{where}: histograms must carry a base-unit suffix "
            f"({' or '.join(_UNIT_SUFFIXES)})")
    if not help_frag.strip():
        problems.append(
            f"{where}: empty or missing help string (the # HELP "
            "line is required documentation)")
    if name not in readme_text:
        problems.append(
            f"{where}: not documented in the README observability "
            "table (add the FULL series name)")
    return problems


def check(series: List[Tuple[str, str, str, str]],
          readme_text: str) -> List[str]:
    """Returns the list of violations (empty = clean)."""
    problems = []
    for kind, name, help_frag, path in series:
        problems.extend(_series_problems(
            kind, name, help_frag, f"{name} ({kind}, {path})",
            readme_text))
    return problems


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------
def _literal_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


@register
class MetricNaming(Rule):
    id = "metric-naming"
    family = "obs"
    severity = "error"
    invariant = ("every registered paddle_tpu_* series follows the "
                 "naming conventions (prefix, _total counters, unit-"
                 "suffixed histograms, non-empty help) and appears "
                 "verbatim in the README observability table")
    history = ("tier-1-wired since PR 3 as tools/check_metric_names.py "
               "— a series cannot land undocumented or misnamed; the "
               "CLI survives as a shim over this rule")

    def check(self, mod):
        seen: Dict[Tuple[str, str], Tuple[int, str]] = {}
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute) and
                    node.func.attr in ("counter", "gauge", "histogram")):
                continue
            name = _literal_str(node.args[0]) if node.args else None
            if name is None:
                continue
            kind = node.func.attr
            help_frag = ""
            if len(node.args) > 1:
                help_frag = _literal_str(node.args[1]) or ""
            key = (kind, name)
            line, best = seen.get(key, (node.lineno, ""))
            # registrations are get-or-create: audit each (kind, name)
            # once per file, with the best help string seen
            seen[key] = (min(line, node.lineno),
                         max(best, help_frag, key=len))
        for (kind, name), (line, help_frag) in sorted(seen.items()):
            for p in _series_problems(kind, name, help_frag, name,
                                      mod.project.readme):
                yield self.finding(mod, line, p)


def _readme_missing(name: str, readme: str) -> bool:
    return name not in readme


@register
class SpanNaming(Rule):
    id = "span-naming"
    family = "obs"
    severity = "error"
    invariant = ("every trace span / event name recorded via "
                 "span(...)/add_event(...) is a registered, README-"
                 "documented name — operators grep a span name from a "
                 "trace straight to its documentation")
    history = ("extends the PR 3 metric-name audit to the span "
               "namespace: request-tree debugging (PR 4) only works "
               "when span names are a closed, documented set")

    def check(self, mod):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = U.dotted(node.func) or ""
            leaf = d.split(".")[-1]
            if leaf not in ("span", "add_event") or not node.args:
                continue
            name = _literal_str(node.args[0])
            if name is None:
                continue
            if _readme_missing(name, mod.project.readme):
                yield self.finding(
                    mod, node.lineno,
                    f"span/event name '{name}' is not documented in "
                    "the README span-name table (add the FULL name)")


@register
class FaultPointNaming(Rule):
    id = "fault-point-naming"
    family = "obs"
    severity = "error"
    invariant = ("every resilience fault point compiled into the "
                 "runtime (fault_point(\"...\") sites) is listed in "
                 "the README fault-tolerance section")
    history = ("chaos tests target fault points by name; an "
               "undocumented point is chaos coverage nobody knows "
               "exists")

    def check(self, mod):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = U.dotted(node.func) or ""
            if d.split(".")[-1] != "fault_point" or not node.args:
                continue
            name = _literal_str(node.args[0])
            if name is None:
                continue
            if _readme_missing(name, mod.project.readme):
                yield self.finding(
                    mod, node.lineno,
                    f"fault point '{name}' is not documented in the "
                    "README fault-tolerance section (Registered "
                    "points list)")


@register
class FlightReasonDocumented(Rule):
    id = "flight-reason-documented"
    family = "obs"
    severity = "error"
    invariant = ("every flight-recorder trigger reason — the "
                 "TRIGGER_REASONS registry and every literal "
                 "flight.trigger(\"...\") site under "
                 "paddle_tpu/observability/ — appears verbatim in the "
                 "README flight-recorder documentation (the series-"
                 "table reason enum / trigger prose)")
    history = ("collective_skew (PR 14) was documented only by manual "
               "convention; numerics_divergence (ISSUE 15) made the "
               "convention a rule — an operator must be able to grep "
               "any bundle directory's reason straight to its docs")

    def check(self, mod):
        if not mod.path.startswith("paddle_tpu/observability/"):
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign):
                names = [t.id for t in node.targets
                         if isinstance(t, ast.Name)]
                if "TRIGGER_REASONS" in names and isinstance(
                        node.value, (ast.Tuple, ast.List)):
                    for elt in node.value.elts:
                        reason = _literal_str(elt)
                        if reason and _readme_missing(
                                reason, mod.project.readme):
                            yield self.finding(
                                mod, elt.lineno,
                                f"flight trigger reason '{reason}' "
                                "(TRIGGER_REASONS) is not documented "
                                "in the README flight-recorder tables")
            if isinstance(node, ast.Call):
                d = U.dotted(node.func) or ""
                if d.split(".")[-1] != "trigger" or not node.args:
                    continue
                reason = _literal_str(node.args[0])
                if reason and _readme_missing(reason,
                                              mod.project.readme):
                    yield self.finding(
                        mod, node.lineno,
                        f"flight trigger reason '{reason}' is not "
                        "documented in the README flight-recorder "
                        "tables")


@register
class CollectiveInstrumentation(Rule):
    id = "collective-instrumentation"
    family = "obs"
    severity = "error"
    invariant = ("every public collective in "
                 "distributed/communication.py records through the "
                 "observability comms layer (a comms.start/finish/"
                 "count call in its body) — a future collective "
                 "cannot ship dark")
    history = ("PR 14: the communication layer ran dark through 13 "
               "PRs (zero spans/series across every collective) right "
               "as the multi-process GSPMD fleet work starts "
               "depending on collective latency, bandwidth and "
               "straggler lines")

    # collectives without the sync_op signature marker that must still
    # record (barrier blocks, ppermute moves payload in-trace);
    # axis_index is deliberately absent — it reads a rank index, no
    # payload moves
    EXTRA_COLLECTIVES = ("barrier", "ppermute", "batch_isend_irecv")

    def check(self, mod):
        if not mod.path.endswith("distributed/communication.py"):
            return
        for node in mod.tree.body:
            if not isinstance(node, ast.FunctionDef) or \
                    node.name.startswith("_"):
                continue
            params = {a.arg for a in (node.args.args
                                      + node.args.kwonlyargs)}
            if "sync_op" not in params and \
                    node.name not in self.EXTRA_COLLECTIVES:
                continue
            records = any(
                isinstance(n, ast.Call)
                and (U.dotted(n.func) or "").split(".")[0]
                in ("comms", "_comms")
                for n in ast.walk(node))
            if not records:
                yield self.finding(
                    mod, node.lineno,
                    f"public collective '{node.name}' never records "
                    "through the observability comms layer "
                    "(observability.comms start/finish or count)")


@register
class StatsKeyNaming(Rule):
    id = "stats-key-naming"
    family = "obs"
    severity = "error"
    invariant = ("every engine.stats key (the _EngineStats dict) is "
                 "README-documented — bench and tests read these keys "
                 "as a public contract")
    history = ("the test_observability key-list contract pins the "
               "exact stats key set; the README table is the operator-"
               "facing half of the same contract")

    def check(self, mod):
        # scoped to modules that define/use _EngineStats so arbitrary
        # stats dicts elsewhere (e.g. HostEmbedding.stats) keep their
        # own namespace
        if "_EngineStats" not in mod.src:
            return
        keys: Dict[str, int] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    (U.dotted(node.func) or "").endswith("_EngineStats"):
                for kw in node.keywords:
                    if kw.arg and kw.arg not in keys:
                        keys[kw.arg] = node.lineno
            if isinstance(node, ast.Subscript):
                base = U.dotted(node.value) or ""
                if base.split(".")[-1] == "stats":
                    key = _literal_str(node.slice)
                    if key is not None and key not in keys:
                        keys[key] = node.lineno
        for key, line in sorted(keys.items(), key=lambda kv: kv[1]):
            if _readme_missing(key, mod.project.readme):
                yield self.finding(
                    mod, line,
                    f"engine.stats key '{key}' is not documented in "
                    "the README engine.stats table")


@register
class AutopilotActionDocumented(Rule):
    id = "autopilot-action-documented"
    family = "obs"
    severity = "error"
    invariant = ("every remediation action the autopilot supervisor "
                 "can commit — literal action names in act(\"...\") "
                 "calls and {\"action\": \"...\"} journal entries "
                 "under paddle_tpu/resilience/ — appears verbatim in "
                 "the README Training-autopilot policy table")
    history = ("ISSUE 16: remediation actions are what an operator "
               "sees in episode timelines, autopilot_remediation "
               "bundles and the paddle_tpu_autopilot_actions_total "
               "series; an action name the README policy table does "
               "not carry is a remediation nobody can audit")

    def check(self, mod):
        if not mod.path.startswith("paddle_tpu/resilience/"):
            return
        seen: Dict[str, int] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                d = U.dotted(node.func) or ""
                if d.split(".")[-1] == "act" and node.args:
                    name = _literal_str(node.args[0])
                    if name is not None and name not in seen:
                        seen[name] = node.lineno
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if _literal_str(k) == "action":
                        name = _literal_str(v)
                        if name is not None and name not in seen:
                            seen[name] = v.lineno
        for name, line in sorted(seen.items(), key=lambda kv: kv[1]):
            if _readme_missing(name, mod.project.readme):
                yield self.finding(
                    mod, line,
                    f"autopilot action '{name}' is not documented in "
                    "the README Training-autopilot policy table")


@register
class AutoscaleActionDocumented(Rule):
    id = "autoscale-action-documented"
    family = "obs"
    severity = "error"
    invariant = ("every scale action the serving autoscaler can "
                 "commit — literals in the SCALE_ACTIONS vocabulary "
                 "and first-argument literals of _decide(\"...\") "
                 "calls under paddle_tpu/inference/autoscaler.py — "
                 "appears verbatim in the README Serving-SLO-control-"
                 "plane section")
    history = ("ISSUE 19: scale actions are what an operator sees in "
               "the scale journal, autoscale_decision bundles and the "
               "paddle_tpu_autoscaler_decisions_total series; an "
               "action name the README does not carry is a fleet-size "
               "change nobody can audit")

    def check(self, mod):
        if not mod.path.startswith("paddle_tpu/inference/autoscaler"):
            return
        seen: Dict[str, int] = {}
        for node in ast.walk(mod.tree):
            # the closed vocabulary: SCALE_ACTIONS = ("grow", ...)
            if isinstance(node, ast.Assign):
                targets = [U.dotted(t) or "" for t in node.targets]
                if any(t.split(".")[-1] == "SCALE_ACTIONS"
                       for t in targets) and \
                        isinstance(node.value, (ast.Tuple, ast.List)):
                    for el in node.value.elts:
                        name = _literal_str(el)
                        if name is not None and name not in seen:
                            seen[name] = el.lineno
            # commit sites: self._decide("grow", ...)
            if isinstance(node, ast.Call):
                d = U.dotted(node.func) or ""
                if d.split(".")[-1] == "_decide" and node.args:
                    name = _literal_str(node.args[0])
                    if name is not None and name not in seen:
                        seen[name] = node.lineno
        for name, line in sorted(seen.items(), key=lambda kv: kv[1]):
            if _readme_missing(name, mod.project.readme):
                yield self.finding(
                    mod, line,
                    f"autoscaler action '{name}' is not documented in "
                    "the README Serving SLO control plane section")


@register
class RoleLiteralDocumented(Rule):
    id = "role-literal-documented"
    family = "obs"
    severity = "error"
    invariant = ("every pool-role / process_role string the serving "
                 "stack can stamp on a replica — literals in *ROLES* "
                 "tuple vocabularies and role=/process_role= keyword "
                 "literals under paddle_tpu/inference/ — appears "
                 "verbatim in the README")
    history = ("ISSUE 20: role strings split fleet telemetry, "
               "capacity lines and perf-ledger baselines per pool "
               "(engine_prefill vs engine_decode); a role value the "
               "README does not carry is a telemetry partition an "
               "operator cannot interpret")

    def check(self, mod):
        if not mod.path.startswith("paddle_tpu/inference/"):
            return
        seen: Dict[str, int] = {}
        for node in ast.walk(mod.tree):
            # closed vocabularies: ROLES / PROCESS_ROLES = ("...",)
            if isinstance(node, ast.Assign):
                targets = [U.dotted(t) or "" for t in node.targets]
                if any(t.split(".")[-1].endswith("ROLES")
                       for t in targets) and \
                        isinstance(node.value, (ast.Tuple, ast.List)):
                    for el in node.value.elts:
                        name = _literal_str(el)
                        if name is not None and name not in seen:
                            seen[name] = el.lineno
            # hand-off sites: factory(role="engine_prefill"),
            # set_identity(process_role="...")
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg in ("role", "process_role"):
                        name = _literal_str(kw.value)
                        if name is not None and name not in seen:
                            seen[name] = kw.value.lineno
        for name, line in sorted(seen.items(), key=lambda kv: kv[1]):
            if _readme_missing(name, mod.project.readme):
                yield self.finding(
                    mod, line,
                    f"replica role '{name}' is not documented in the "
                    "README Prefill/decode disaggregation section")
