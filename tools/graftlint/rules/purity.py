"""Trace-purity / host-sync rules (family: purity).

The invariant: code that runs UNDER A JAX TRACE — jitted functions,
``lax.scan``/``while_loop``/``cond`` bodies, Pallas kernels — must stay
device-pure. Host materialization (``.item()``, ``np.asarray``,
``jax.device_get``, ``block_until_ready``, ``float()``/``int()`` on a
traced value) either fails at trace time or, worse, silently bakes a
trace-time constant into the executable; ``time.*`` and ``print``
execute once at trace time and never again, which is a classic
recompile-debugging trap.

Outside traces, host materialization is legal but EXPENSIVE: each one
is a device->host round trip on the dispatch path (ROADMAP item 4:
``eager_over_trainstep`` 1.74 vs the <=1.5 target is exactly
accumulated round-trip cost). ``host-sync`` (warning) inventories
every such site so the count only goes DOWN — existing sites are
grandfathered in the baseline; a new one must either be justified into
the baseline or kept off the host.

Reachability is static and deliberately shallow: a function is
"traced" when it is decorated with / passed to a tracing entry point,
or when it is called BY a traced function via a bare name defined in
the same module (one level of call graph — deeper indirection should
be refactored, not chased)."""
from __future__ import annotations

import ast
from typing import Dict, Optional

from ..core import Rule, register
from . import _util as U

# tracing entry points: dotted-suffix -> positions of traced callables.
# Ambiguous bare names (scan, cond, map, grad, checkpoint, remat) must
# carry a qualifier (jax./lax./pl.) to count.
_QUALIFIED = {
    "scan": (0,), "while_loop": (0, 1), "fori_loop": (2,),
    "cond": (1, 2), "switch": (1,), "associative_scan": (0,),
    "map": (0,), "grad": (0,), "value_and_grad": (0,),
    "checkpoint": (0,), "remat": (0,),
}
_UNQUALIFIED = {
    "jit": (0,), "pallas_call": (0,), "while_loop": (0, 1),
    "fori_loop": (2,), "vmap": (0,), "pmap": (0,),
    "value_and_grad": (0,), "associative_scan": (0,),
}
_QUALIFIERS = ("jax", "lax", "pl", "pallas", "plgpu", "pltpu")


def _trace_positions(call: ast.Call):
    d = U.dotted(call.func)
    if d is None:
        return None
    parts = d.split(".")
    leaf = parts[-1]
    if len(parts) > 1 and parts[-2] in _QUALIFIERS or \
            len(parts) > 2 and parts[0] in _QUALIFIERS:
        hit = _QUALIFIED.get(leaf) or _UNQUALIFIED.get(leaf)
        return hit
    return _UNQUALIFIED.get(leaf)


def _jit_decorated(fn) -> bool:
    for dec in getattr(fn, "decorator_list", ()):
        node = dec.func if isinstance(dec, ast.Call) else dec
        d = U.dotted(node) or ""
        leaf = d.split(".")[-1]
        if leaf == "jit":
            return True
        if leaf == "partial" and isinstance(dec, ast.Call) and dec.args:
            inner = U.dotted(dec.args[0]) or ""
            if inner.split(".")[-1] == "jit":
                return True
    return False


def traced_functions(mod) -> Dict[ast.AST, str]:
    """FunctionDef/Lambda -> reason string for everything that runs
    under a trace in this module (incl. the one-level call walk).
    Cached on the Module (both purity rules consume it)."""
    hit = mod.cache.get("traced_functions")
    if hit is not None:
        return hit
    out: Dict[ast.AST, str] = {}
    mod.cache["traced_functions"] = out

    def mark(fn, reason):
        if fn is not None and fn not in out:
            out[fn] = reason

    scope_of = {}
    for scope in U.mod_scopes(mod):
        for node in U.mod_own_body(mod, scope):
            scope_of[node] = scope

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _jit_decorated(node):
                mark(node, f"decorated jit function '{node.name}'")
        if not isinstance(node, ast.Call):
            continue
        pos = _trace_positions(node)
        if pos is None:
            continue
        entry = U.dotted(node.func)
        scope = scope_of.get(node, mod.tree)
        for i in pos:
            if i >= len(node.args):
                continue
            arg = node.args[i]
            if isinstance(arg, ast.Lambda):
                mark(arg, f"lambda passed to {entry}")
            elif isinstance(arg, ast.Name):
                fn = U.resolve_function(arg.id, scope, mod.tree)
                if fn is not None:
                    mark(fn, f"'{fn.name}' passed to {entry}")

    # one-level call-graph walk: bare-name calls from a traced body
    for fn, reason in list(out.items()):
        if isinstance(fn, ast.Lambda):
            continue
        scope = scope_of.get(fn, mod.tree)
        for node in U.own_body_nodes(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name):
                callee = U.resolve_function(node.func.id, fn, mod.tree) \
                    or U.resolve_function(node.func.id, scope, mod.tree)
                if callee is not None and callee not in out:
                    out[callee] = (f"'{callee.name}' called from traced "
                                   f"{reason}")
    return out


def _numpy_call(d: str) -> bool:
    parts = d.split(".")
    return len(parts) > 1 and parts[0] in ("np", "numpy") and \
        parts[-1] in ("asarray", "array")


def _host_sync_why(node: ast.Call) -> Optional[str]:
    """Reason `node` is a host materialization, else None. The shared
    pattern set of both purity rules."""
    if isinstance(node.func, ast.Attribute):
        if node.func.attr == "item" and not node.args:
            return ".item() forces a device->host transfer"
        if node.func.attr == "block_until_ready":
            return "block_until_ready() synchronizes with the device"
    d = U.dotted(node.func) or ""
    if _numpy_call(d):
        return f"{d}() materializes the value on the host"
    if d in ("jax.device_get", "device_get"):
        return "jax.device_get() copies device memory to the host"
    if d == "jax.block_until_ready":
        return "jax.block_until_ready() synchronizes with the device"
    return None


def _trace_only_why(node: ast.Call) -> Optional[str]:
    """Patterns flagged ONLY under a trace (legal, if slow, on the
    host): float()/int() coercion, wall clocks, print."""
    d = U.dotted(node.func) or ""
    if d in ("float", "int") and node.args and \
            not isinstance(node.args[0], ast.Constant):
        return (f"{d}() on a traced value forces host materialization "
                "(or bakes a trace-time constant)")
    if d.startswith("time.") or d.startswith("_time."):
        return (f"{d}() reads the host clock — under a trace it runs "
                "ONCE at trace time and becomes a constant")
    if d == "print":
        return ("print() executes at trace time only; use "
                "jax.debug.print for runtime values")
    return None


@register
class HostSyncInTrace(Rule):
    id = "host-sync-in-trace"
    family = "purity"
    severity = "error"
    invariant = ("functions that run under a jax trace (jit, "
                 "scan/while/cond bodies, Pallas kernels, one bare-name"
                 " call away) must not touch the host: no .item()/"
                 "np.asarray/device_get/block_until_ready/float()/"
                 "int()/time.*/print")
    history = ("host round-trips inside hot dispatch paths are the "
               "measured eager_over_trainstep ceiling (ROADMAP item 4:"
               " 1.74 vs <=1.5); trace-time clocks/prints are classic "
               "silent-constant bugs")

    def check(self, mod):
        traced = traced_functions(mod)
        for fn, reason in traced.items():
            nodes = []
            if isinstance(fn, ast.Lambda):
                nodes = list(ast.walk(fn.body))
            else:
                # include nested defs (inner helpers execute under the
                # same trace) EXCEPT ones independently traced — those
                # get their own walk, and double-visiting would count
                # one violation twice in the baseline/bench numbers
                stack = list(fn.body)
                while stack:
                    node = stack.pop()
                    if node is not fn and node in traced:
                        continue
                    nodes.append(node)
                    stack.extend(ast.iter_child_nodes(node))
            for node in nodes:
                if not isinstance(node, ast.Call):
                    continue
                why = _host_sync_why(node) or _trace_only_why(node)
                if why:
                    yield self.finding(
                        mod, node.lineno,
                        f"{why} — inside {reason}, which runs under a "
                        "jax trace")


_CLOCK_LEAVES = ("time", "perf_counter", "perf_counter_ns",
                 "monotonic", "monotonic_ns")


@register
class HostClockInDispatch(Rule):
    id = "host-clock-in-dispatch"
    family = "purity"
    severity = "warning"
    invariant = ("wall-clock reads (time.time/perf_counter/monotonic) "
                 "on the eager dispatch hot path (autograd/, "
                 "ops/registry.py) are per-dispatch host overhead: "
                 "every site is inventoried and carries a baseline "
                 "justification — gap-measurement sites must be one "
                 "flag check when observability is off")
    history = ("the dispatch-gap profiler (PR 8) and the batched "
               "backward engine (ISSUE 10) both live on this path; "
               "an unguarded clock read per grad node is exactly the "
               "class of overhead that kept eager_over_trainstep at "
               "1.74")
    baseline_note = ("host-clock-in-dispatch: audited wall-clock read "
                     "on the dispatch hot path — keep behind the "
                     "observability flag")

    def check(self, mod):
        from .. import config as _cfg
        if not any(mod.path == p or
                   (p.endswith("/") and mod.path.startswith(p))
                   for p in _cfg.DISPATCH_CLOCK_AUDIT_PATHS):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = U.dotted(node.func) or ""
            if d.startswith(("time.", "_time.")) and \
                    d.split(".")[-1] in _CLOCK_LEAVES:
                yield self.finding(
                    mod, node.lineno,
                    f"{d}() reads the host clock on the eager "
                    "dispatch hot path")


@register
class HostSync(Rule):
    id = "host-sync"
    family = "purity"
    severity = "warning"
    invariant = ("host materialization (.item(), np.asarray, "
                 "jax.device_get, block_until_ready) on library paths "
                 "is a device->host round trip: every site is "
                 "inventoried, existing ones are baselined, and the "
                 "count must only go down")
    history = ("per-grad-node host round-trips keep "
               "eager_over_trainstep at 1.74 (target <=1.5, ROADMAP "
               "item 4) — the burn-down list lives in the baseline")
    baseline_note = ("host-sync: grandfathered host materialization "
                     "(pre-graftlint inventory) — burn down by keeping "
                     "values on device, ROADMAP item 4")

    def check(self, mod):
        traced = set()
        for fn in traced_functions(mod):
            traced.update(id(n) for n in ast.walk(fn))
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or id(node) in traced:
                continue
            why = _host_sync_why(node)
            if why:
                yield self.finding(mod, node.lineno, why)
