"""Recompile-hazard rules (family: recompile).

The invariant: the identity of an XLA executable is its cache key. A
key component that is UNSTABLE (default object ``repr`` embeds the
memory address; ``id()`` is the address; an f-string hides type
coercion) mints a fresh key per instance/process — each one a silent
mid-serving recompile, the class of incident the engine's
``_CompileTimed`` compile telemetry exists to catch. Likewise a
``static_argnums`` position bound to an unhashable object (list/dict/
set) fails at dispatch, and one bound to an object without value-based
``__hash__``/``__eq__`` recompiles per instance.

This PR's motivating sites: the fused optimizer's
``_hyper_fingerprint`` (``repr(wd)`` of a weight-decay object =
per-instance key) and its group-hyper fallback ``repr(items)`` — both
fixed to structural fingerprints in the same change that lands this
rule. The engine's executable cache (``LLMEngine._fns``) keys on
shape/dtype tuples and stays clean.
"""
from __future__ import annotations

import ast
import re

from ..core import Rule, register
from . import _util as U

# function names that build cache keys / fingerprints
_KEYFN_RE = re.compile(
    r"fingerprint|cache_key|cachekey|hyper_fp|(^|_)fp$|_key$")
# container names that are executable/compile caches
_CACHE_RE = re.compile(r"cache|_fns$|_executables?$", re.IGNORECASE)
# dict verbs through which a key reaches an in-memory cache
_DICT_METHODS = ("get", "setdefault", "pop")
# the persistent-store surface (exec_cache.ExecCache and kin): keys
# passed to these verbs reach DISK, where an unstable component is
# strictly worse than an in-memory one — a repr()-keyed entry is
# never hit again AND accumulates forever. Receivers are matched more
# broadly (*cache*/*store*) because the verbs themselves are specific;
# plain identity maps (e.g. an id()-keyed node_store dict) don't
# speak this surface.
_STORE_RE = re.compile(r"cache|store", re.IGNORECASE)
_STORE_METHODS = ("load", "save", "put", "verify", "remove")


def _unstable_why(node) -> str:
    """Reason `node` is an unstable key component, else ''."""
    if isinstance(node, ast.Call):
        d = U.dotted(node.func)
        if d == "repr" and node.args:
            return ("repr() of an object without a value-based __repr__"
                    " embeds the memory address — a fresh instance "
                    "mints a fresh executable-cache key (silent "
                    "recompile)")
        if d == "id" and node.args:
            return ("id() is the memory address — per-instance cache "
                    "keys recompile on every new object")
    if isinstance(node, ast.JoinedStr):
        for v in node.values:
            if isinstance(v, ast.FormattedValue):
                return ("f-string-built key components hide type/format"
                        " coercions (1 vs 1.0 vs True collide or "
                        "diverge silently) — key on the structured "
                        "values themselves")
    return ""


def _cache_name(node) -> bool:
    """`node` names a cache-like container (`cache[...]`,
    `self._fns[...]`)."""
    d = U.dotted(node)
    if not d:
        return False
    leaf = d.split(".")[-1]
    return bool(_CACHE_RE.search(leaf))


def _store_name(node) -> bool:
    """`node` names a persistent-store-like object (`store.load(...)`,
    `self._exec_cache.save(...)`)."""
    d = U.dotted(node)
    if not d:
        return False
    leaf = d.split(".")[-1]
    return bool(_STORE_RE.search(leaf))


@register
class UnstableCacheKey(Rule):
    id = "unstable-cache-key"
    family = "recompile"
    severity = "error"
    invariant = ("executable-cache keys and fingerprints must be built "
                 "from stable, value-comparable components — never "
                 "repr()/id() of arbitrary objects or f-strings")
    history = ("the fused-optimizer _hyper_fingerprint repr() fallback "
               "made two equal-valued decay objects key differently "
               "(one recompile per instance); pinned verify widths in "
               "spec-decode exist because signature drift = mid-"
               "serving XLA compiles")

    def check(self, mod):
        # 1. inside key-builder functions
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and _KEYFN_RE.search(node.name.lower()):
                for sub in ast.walk(node):
                    why = _unstable_why(sub)
                    if why:
                        yield self.finding(
                            mod, sub.lineno,
                            f"in key-builder '{node.name}': {why}")
        # 2. expressions used directly as cache keys, and the
        #    one-assignment-back construction of key variables
        for scope in U.mod_scopes(mod):
            key_names = set()
            nodes = U.mod_own_body(mod, scope)
            for node in nodes:
                key_exprs = []
                if isinstance(node, ast.Subscript) and \
                        _cache_name(node.value):
                    key_exprs.append(node.slice)
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        ((node.func.attr in _DICT_METHODS
                          and _cache_name(node.func.value))
                         or (node.func.attr in _STORE_METHODS
                             and _store_name(node.func.value))) \
                        and node.args:
                    key_exprs.append(node.args[0])
                for ke in key_exprs:
                    for sub in ast.walk(ke):
                        why = _unstable_why(sub)
                        if why:
                            yield self.finding(
                                mod, sub.lineno,
                                f"in executable-cache key: {why}")
                    if isinstance(ke, ast.Name):
                        key_names.add(ke.id)
            if not key_names:
                continue
            for node in nodes:
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id in key_names
                        for t in node.targets):
                    for sub in ast.walk(node.value):
                        why = _unstable_why(sub)
                        if why:
                            yield self.finding(
                                mod, sub.lineno,
                                "in the construction of cache key "
                                f"'{[t.id for t in node.targets if isinstance(t, ast.Name)][0]}'"
                                f": {why}")


@register
class UnhashableStaticArg(Rule):
    id = "unhashable-static-arg"
    family = "recompile"
    severity = "error"
    invariant = ("static_argnums positions must receive hashable, "
                 "value-comparable arguments — a list/dict/set fails at"
                 " dispatch, an identity-hashed object recompiles per "
                 "instance")
    history = ("static-arg signature drift is the same incident class "
               "as the spec-decode verify-width pin: every new "
               "signature is a mid-serving XLA compile")

    _UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                   ast.SetComp, ast.DictComp, ast.GeneratorExp)

    def check(self, mod):
        for scope in U.mod_scopes(mod):
            for node in U.mod_own_body(mod, scope):
                if not U.is_jit_call(node):
                    continue
                kw = U.keyword(node, "static_argnums")
                if kw is None:
                    continue
                nums = U.const_int_seq(kw)
                if not nums:
                    continue
                args, call = U.call_arg_vector(mod, node, scope)
                if args is None:
                    continue
                for i in nums:
                    if i >= len(args):
                        continue
                    a = args[i]
                    bad = isinstance(a, self._UNHASHABLE) or (
                        isinstance(a, ast.Call) and
                        U.dotted(a.func) in ("list", "dict", "set"))
                    if bad:
                        yield self.finding(
                            mod, a.lineno,
                            f"static_argnums position {i} receives "
                            f"'{U.unparse(a)}' — unhashable static "
                            "arguments fail at dispatch time; pass a "
                            "tuple / frozen value instead")
