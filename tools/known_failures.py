#!/usr/bin/env python
"""Machine-checkable "no NEW tier-1 failures".

The CPU test box has a fixed set of ENVIRONMENT failures (jax too old
for jax.shard_map, no multi-process CPU backend — see
tools/known_failures.json) that every tier-1 run reports. "Tests no
worse than the seed" used to mean eyeballing the failure list against
a prose note; this tool makes it a gate:

    set -o pipefail
    ... python -m pytest tests/ -q ... | tee /tmp/_t1.log
    python tools/known_failures.py /tmp/_t1.log

Exit 0 when every FAILED/ERROR nodeid in the log is in the manifest
(known environment failures may also be ABSENT — a fix is progress,
reported as such); exit 1 listing each NEW failure otherwise. Entries
under "flaky" (timing-sensitive tests that measure real wall clocks
on a shared box) are reported when they fail but never fatal — rerun
them standalone before treating one as a regression.

`--staleness` audits the manifest itself: entries whose nodeid no
longer exists in the tree (file deleted, test renamed) or that did
not fail this run are flagged so the manifest tracks reality instead
of accreting dead entries. The staleness report is informational —
it never changes the exit code — and a one-line summary rides every
default run so drift is visible without asking for it.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Dict, List, Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
DEFAULT_MANIFEST = os.path.join(_HERE, "known_failures.json")


def load_manifest(path: Optional[str] = None) -> Dict:
    with open(path or DEFAULT_MANIFEST, encoding="utf-8") as f:
        m = json.load(f)
    for key in ("failures", "flaky"):
        if not isinstance(m.get(key), list):
            raise ValueError(
                f"manifest {path or DEFAULT_MANIFEST}: missing or "
                f"non-list {key!r} key")
    return m


def parse_failures(text: str) -> List[str]:
    """Failed/errored nodeids from a pytest -q log, deduped in first-
    seen order (the summary can repeat a nodeid, e.g. a test that both
    failed and errored at teardown)."""
    seen, out = set(), []
    for line in text.splitlines():
        if not line.startswith(("FAILED ", "ERROR ")):
            continue
        parts = line.split()
        if len(parts) < 2:
            continue
        nodeid = parts[1]
        if nodeid not in seen:
            seen.add(nodeid)
            out.append(nodeid)
    return out


@dataclasses.dataclass
class Report:
    new: List[str]                  # failures NOT in the manifest
    known_seen: List[str]           # manifest failures that occurred
    known_missing: List[str]        # manifest failures that did NOT
    flaky_seen: List[str]           # flaky tests that failed this run

    @property
    def ok(self) -> bool:
        return not self.new


def check_log(log_path: str, manifest_path: Optional[str] = None
              ) -> Report:
    m = load_manifest(manifest_path)
    with open(log_path, encoding="utf-8", errors="replace") as f:
        failed = parse_failures(f.read())
    known = set(m["failures"])
    flaky = set(m["flaky"])
    return Report(
        new=[n for n in failed if n not in known and n not in flaky],
        known_seen=[n for n in failed if n in known],
        known_missing=sorted(known - set(failed)),
        flaky_seen=[n for n in failed if n in flaky],
    )


def classify_staleness(manifest: Dict, failed: List[str],
                       root: Optional[str] = None) -> Dict[str, List[str]]:
    """Audit manifest entries (failures + flaky) against the tree and
    this run's failure set. Buckets:

    - "file_missing": the test file no longer exists — the entry is
      definitely stale, delete it.
    - "test_missing": the file exists but defines no matching test
      function — renamed or removed, delete or update the entry.
    - "absent_this_run": the test still exists but did not fail this
      run — it may pass now (fixed? environment changed?) or simply
      have been deselected; candidate for manifest removal after a
      full-tree run confirms it.
    """
    root = root or os.path.dirname(_HERE)
    failed_set = set(failed)
    out: Dict[str, List[str]] = {
        "file_missing": [], "test_missing": [], "absent_this_run": []}
    src_cache: Dict[str, Optional[str]] = {}
    for nodeid in sorted(set(manifest["failures"]) | set(manifest["flaky"])):
        path = nodeid.split("::", 1)[0]
        fpath = os.path.join(root, path)
        if fpath not in src_cache:
            try:
                with open(fpath, encoding="utf-8") as f:
                    src_cache[fpath] = f.read()
            except OSError:
                src_cache[fpath] = None
        src = src_cache[fpath]
        if src is None:
            out["file_missing"].append(nodeid)
            continue
        # last :: component is the test function; strip the
        # parametrization id ("test_x[cpu-4]" -> "test_x")
        name = nodeid.rsplit("::", 1)[-1].split("[", 1)[0]
        if f"def {name}" not in src:
            out["test_missing"].append(nodeid)
        elif nodeid not in failed_set:
            out["absent_this_run"].append(nodeid)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="check a tier-1 pytest log against the known-"
                    "environment-failure manifest")
    ap.add_argument("log", help="pytest output log (tee of tier-1)")
    ap.add_argument("--manifest", default=None,
                    help=f"manifest path (default {DEFAULT_MANIFEST})")
    ap.add_argument("--staleness", action="store_true",
                    help="print the detailed manifest-staleness audit "
                         "(entries whose nodeid no longer exists or "
                         "that did not fail this run); never fatal")
    args = ap.parse_args(argv)
    r = check_log(args.log, args.manifest)
    print(f"known environment failures seen: {len(r.known_seen)} of "
          f"{len(r.known_seen) + len(r.known_missing)}")
    stale = classify_staleness(
        load_manifest(args.manifest),
        r.new + r.known_seen + r.flaky_seen)
    n_dead = len(stale["file_missing"]) + len(stale["test_missing"])
    print(f"manifest staleness: {n_dead} dead entries, "
          f"{len(stale['absent_this_run'])} absent this run"
          + ("" if args.staleness or not n_dead
             else " (--staleness for details)"))
    if args.staleness:
        for bucket, label in (
                ("file_missing", "test file gone — delete the entry"),
                ("test_missing", "test renamed/removed — update"),
                ("absent_this_run",
                 "did not fail this run (fixed, or deselected)")):
            for n in stale[bucket]:
                print(f"  ? {n}  [{label}]")
    if r.known_missing:
        print("known failures ABSENT this run (fixed? environment "
              "changed? update the manifest):")
        for n in r.known_missing:
            print(f"  - {n}")
    if r.flaky_seen:
        print("flaky (timing-sensitive) failures — rerun standalone "
              "before calling them regressions:")
        for n in r.flaky_seen:
            print(f"  ~ {n}")
    if r.new:
        print(f"NEW failures ({len(r.new)}) — these are regressions:")
        for n in r.new:
            print(f"  ! {n}")
        return 1
    print("no new failures")
    return 0


if __name__ == "__main__":
    sys.exit(main())
