"""Observability smoke CLI: run a short synthetic LLMEngine workload
with metrics + tracing enabled, print the Prometheus exposition, and
write Chrome-trace / JSONL exports — for eyeballing series names and
for bench scripts that want a known-good baseline dump.

    python tools/obs_dump.py [--out /tmp/paddle_tpu_obs]
                             [--requests 6] [--tokens 12] [--json]

Runs on whatever backend jax selects (the tiny GPT config compiles in
seconds on CPU)."""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/tmp/paddle_tpu_obs",
                    help="directory for trace exports")
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--tokens", type=int, default=12)
    ap.add_argument("--json", action="store_true",
                    help="also print the JSON export")
    args = ap.parse_args()

    import paddle_tpu as pt
    from paddle_tpu import observability as obs
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import gpt_tiny
    from paddle_tpu.optimizer import AdamW

    obs.enable()
    obs.reset()

    pt.seed(0)
    model = GPTForCausalLM(gpt_tiny())
    model.eval()
    rng = np.random.default_rng(0)
    eng = LLMEngine(model, max_batch=2, block_size=16, decode_chunk=4,
                    prompt_quantum=16, max_model_len=64)
    prompts = [rng.integers(0, 1024, (int(n),)).astype(np.int32)
               for n in rng.integers(4, 20, args.requests)]
    t0 = time.perf_counter()
    results = eng.generate(prompts, max_new_tokens=args.tokens)
    wall = time.perf_counter() - t0

    # a few fused optimizer steps so the cache-outcome series shows up
    lin = pt.nn.Linear(8, 8)
    opt = AdamW(learning_rate=1e-3, parameters=lin.parameters())
    x = pt.to_tensor(np.ones((2, 8), np.float32))
    for _ in range(3):
        (lin(x) ** 2).mean().backward()
        opt.step()
        opt.clear_grad()

    print(obs.to_prometheus())
    if args.json:
        print(obs.to_json())
    chrome = obs.export_chrome_trace(
        os.path.join(args.out, "engine_trace.json"))
    jsonl = obs.export_jsonl(
        os.path.join(args.out, "engine_trace.jsonl"))
    print(json.dumps({
        "requests": len(results),
        "ok": sum(r.ok for r in results),
        "generated_tokens": int(sum(len(r.output_ids)
                                    for r in results)),
        "wall_s": round(wall, 3),
        "trace_events": len(obs.trace_events()),
        "chrome_trace": chrome,
        "jsonl": jsonl,
    }), flush=True)


if __name__ == "__main__":
    main()
