#!/usr/bin/env python
"""Terminal observability dashboard over the `paddle_tpu` metric
export: tok/s, queue depths, prefix-cache hit rate, TTFT/TPOT
percentiles, compile counts, HBM — the SRE's one-screen answer to
"what is the engine doing right now".

Data sources (exactly one):
    --json FILE     a file containing `obs.to_json()` output,
                    re-read every --interval seconds (a serving
                    process that periodically rewrites the file makes
                    this a live dashboard; rates are computed between
                    frames)
    --bundle DIR    a flight-recorder bundle (renders its
                    metrics.json; implies a single frame unless the
                    bundle is being rewritten)
    --demo          run a short synthetic LLMEngine workload in
                    process and render ONE frame from the live
                    registry (the workload ends before the frame, so
                    there is nothing to watch — --demo implies --once)

    python tools/obs_top.py --demo --once
    python tools/obs_top.py --json /run/paddle_tpu_metrics.json
    python tools/obs_top.py --bundle /var/log/flight/bundle_000001_* --once
    python tools/obs_top.py --json /run/fleet.json --fleet

--fleet renders only the fleet panel (per-process heartbeat age /
staleness, bundle seq, inflight, tok/s) from a fleet aggregator's
export (`observability.fleet.FleetAggregator.export_json`); without
the flag the panel still appears under a full frame whenever the doc
carries fleet series.

--once prints one frame and exits (scriptable); without it the screen
refreshes until Ctrl-C. Percentiles are estimated from the exported
bucket vectors (observability.metrics.quantile_from_buckets), so the
dashboard needs no live registry access."""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu.observability.metrics import (  # noqa: E402
    quantile_from_buckets, quantiles_by_label)


# ---------------------------------------------------------------------------
# doc accessors over the to_json() shape:
#   {name: {kind, help, series: [{labels: {...}, value: v}], buckets?}}
# ---------------------------------------------------------------------------
def _series(doc, name):
    rec = doc.get(name)
    return (rec or {}).get("series", [])


def _value(doc, name, **labels):
    for s in _series(doc, name):
        if s["labels"] == labels:
            return s["value"]
    return None


def _counter_sum(doc, name, **labels):
    total = 0.0
    for s in _series(doc, name):
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            total += s["value"]
    return total


def _hist_quantiles(doc, name, qs=(0.5, 0.95), prev=None):
    """Percentile estimates for a histogram's unlabeled series. With
    `prev` (the previous frame's doc), quantiles come from the
    BETWEEN-FRAMES bucket delta — the live read for high-rate
    histograms like the dispatch-gap profile, where the cumulative
    distribution would bury the last few seconds. Falls back to the
    cumulative series when the delta is empty (idle between frames)."""
    rec = doc.get(name)
    if not rec or rec.get("kind") != "histogram":
        return None
    for s in rec["series"]:
        if s["labels"]:
            continue
        v = s["value"]
        counts, lo, hi = v["buckets"], v["min"], v["max"]
        if prev is not None:
            for ps in (prev.get(name) or {}).get("series", []):
                if ps["labels"]:
                    continue
                dl = [c - p for c, p in zip(counts,
                                            ps["value"]["buckets"])]
                if sum(dl) > 0:
                    # window extrema are unknowable from two
                    # cumulative frames; the bucket grid bounds the
                    # estimate instead
                    counts, lo, hi = dl, None, None
                break
        n = sum(counts)
        if not n:
            return None
        return {
            "count": n,
            **{f"p{int(q * 100)}": quantile_from_buckets(
                rec["buckets"], counts, q, lo=lo, hi=hi)
               for q in qs},
        }
    return None


def _ms(x):
    return "-" if x is None else f"{x * 1e3:8.2f}ms"


# promoted to observability.metrics.quantiles_by_label (PR 19); the
# alias keeps this module's long-standing internal name working
_hist_quantiles_by = quantiles_by_label


def render_fleet(doc, prev=None, dt=None) -> str:
    """The fleet panel: one line per process from an aggregator export
    (`FleetAggregator.to_json()` / `export_json`) — up/STALE from the
    heartbeat-age vs process-up gauges, last accepted bundle seq,
    inflight (that process's running-queue depth), token totals with a
    between-frames tok/s when watching live — plus a fleet-plane
    self-accounting line (bundles, duplicates, quarantined series,
    agent drops). Empty string when the doc carries no fleet series."""
    ages = {s["labels"]["process"]: s["value"] for s in
            _series(doc, "paddle_tpu_fleet_heartbeat_age_seconds")}
    if not ages:
        return ""
    lines = ["== fleet =="]
    for proc in sorted(ages):
        up = _value(doc, "paddle_tpu_fleet_process_up", process=proc)
        seq = _value(doc, "paddle_tpu_fleet_last_seq", process=proc)
        infl = _value(doc, "paddle_tpu_engine_queue_depth",
                      queue="running", process=proc)
        tok = _counter_sum(doc, "paddle_tpu_engine_events_total",
                           event="decode_tokens", process=proc)
        tps = None
        if prev is not None and dt:
            tps = (tok - _counter_sum(
                prev, "paddle_tpu_engine_events_total",
                event="decode_tokens", process=proc)) / dt
        lines.append(
            f"  {proc:<16} {'up' if up else 'STALE':<6} "
            f"hb={ages[proc]:6.1f}s  seq={int(seq or 0):>4}  "
            f"inflight={int(infl or 0):>3}  tok={int(tok):>8}"
            + (f"  ({tps:8.1f} tok/s)" if tps is not None else ""))
    bundles = _counter_sum(doc, "paddle_tpu_fleet_bundles_total")
    dups = _counter_sum(doc, "paddle_tpu_fleet_duplicate_bundles_total")
    quar = _counter_sum(doc, "paddle_tpu_fleet_quarantined_series_total")
    drops = _counter_sum(doc,
                         "paddle_tpu_fleet_agent_dropped_events_total")
    totals = f"  bundles={int(bundles)}  dups={int(dups)}"
    if quar:
        totals += f"  quarantined={int(quar)}"
    if drops:
        totals += f"  agent drops={int(drops)}"
    lines.append(totals)
    return "\n".join(lines)


def render(doc, prev=None, dt=None) -> str:
    """One dashboard frame from a to_json() document. prev/dt: the
    previous frame's doc + seconds between reads, for rates."""
    lines = []

    def rate(name, **labels):
        if prev is None or not dt:
            return None
        d = _counter_sum(doc, name, **labels) - \
            _counter_sum(prev, name, **labels)
        return d / dt

    ev = "paddle_tpu_engine_events_total"
    toks = _counter_sum(doc, ev, event="decode_tokens")
    tps = rate(ev, event="decode_tokens")
    lines.append("== engine ==")
    lines.append(
        f"  tokens out   {int(toks):>10}"
        + (f"   ({tps:8.1f} tok/s)" if tps is not None else ""))
    for k in ("prefills", "decode_chunks", "preemptions",
              "failed_requests", "rejected_requests",
              "deadline_expired"):
        n = _counter_sum(doc, ev, event=k)
        if n:
            lines.append(f"  {k:<12} {int(n):>10}")
    qd = "paddle_tpu_engine_queue_depth"
    wait = _value(doc, qd, queue="waiting")
    run = _value(doc, qd, queue="running")
    if wait is not None or run is not None:
        lines.append(f"  queues       waiting={int(wait or 0)} "
                     f"running={int(run or 0)}")
    pool = "paddle_tpu_engine_page_pool_blocks"
    free = _value(doc, pool, state="free")
    used = _value(doc, pool, state="used")
    if free is not None:
        lines.append(f"  page pool    used={int(used or 0)} "
                     f"free={int(free)}")

    pre = "paddle_tpu_engine_prefix_cache_tokens_total"
    hit = _counter_sum(doc, pre, outcome="hit")
    miss = _counter_sum(doc, pre, outcome="miss")
    if hit + miss:
        lines.append(f"  prefix hit   {hit / (hit + miss):6.1%}  "
                     f"({int(hit)} of {int(hit + miss)} prompt tokens)")

    sp = "paddle_tpu_engine_spec_tokens_total"
    acc = _counter_sum(doc, sp, outcome="accepted")
    rej = _counter_sum(doc, sp, outcome="rejected")
    if acc + rej:
        ar = rate(sp, outcome="accepted")
        lines.append(
            f"  spec accept  {acc / (acc + rej):6.1%}  "
            f"({int(acc)} of {int(acc + rej)} drafted tokens)"
            + (f"   ({ar:8.1f} acc tok/s)" if ar is not None else ""))

    lines.append("== requests ==")
    fin = "paddle_tpu_request_finished_total"
    outcomes = {s["labels"]["reason"]: int(s["value"])
                for s in _series(doc, fin)}
    if outcomes:
        lines.append("  finished     " + "  ".join(
            f"{k}={v}" for k, v in sorted(outcomes.items())))
    for label, name in (
            ("TTFT", "paddle_tpu_request_ttft_seconds"),
            ("TPOT", "paddle_tpu_request_tpot_seconds"),
            ("queue wait", "paddle_tpu_request_queue_wait_seconds"),
            ("e2e", "paddle_tpu_request_e2e_seconds")):
        qv = _hist_quantiles(doc, name)
        if qv:
            lines.append(f"  {label:<12} p50={_ms(qv['p50'])}  "
                         f"p95={_ms(qv['p95'])}  n={qv['count']}")
    br = _series(doc, "paddle_tpu_slo_breaches_total")
    if br:
        lines.append("  SLO breaches " + "  ".join(
            f"{s['labels']['slo']}={int(s['value'])}" for s in br))

    # replicated serving: per-replica health + fleet failover totals
    # (present only when a Router is running)
    states = {}
    for s in _series(doc, "paddle_tpu_router_replica_state"):
        if s["value"]:
            states[s["labels"]["replica"]] = s["labels"]["state"]
    # per-PROCESS rows (fleet-merged docs: replicas running as real OS
    # processes): pid + role from the heartbeat join series, capacity
    # rates from the aggregator's capacity gauges, exec-cache
    # reintegration split from the merged compile counter
    procs = {}
    for s in _series(doc, "paddle_tpu_fleet_process_pid"):
        procs[s["labels"]["process"]] = {
            "pid": int(s["value"]),
            "role": s["labels"].get("role", "")}
    if states or procs:
        lines.append("== replicas ==")
        for rep in sorted(states):
            infl = _value(doc, "paddle_tpu_router_replica_inflight",
                          replica=rep)
            lines.append(f"  {rep:<12} {states[rep]:<10} "
                         f"inflight={int(infl or 0)}")
        for proc in sorted(procs):
            info = procs[proc]
            req = _value(doc, "paddle_tpu_fleet_capacity_req_per_s",
                         process=proc)
            tok = _value(doc, "paddle_tpu_fleet_capacity_tok_per_s",
                         process=proc)
            hit = _counter_sum(doc, "paddle_tpu_compile_total",
                               process=proc, outcome="disk_hit")
            miss = _counter_sum(doc, "paddle_tpu_compile_total",
                                process=proc, outcome="compile")
            row = (f"  {proc:<12} pid={info['pid']:<7} "
                   f"{info['role']:<8}")
            row += (f" req/s={req:6.2f}" if req is not None
                    else " req/s=     -")
            row += (f" tok/s={tok:7.1f}" if tok is not None
                    else " tok/s=      -")
            if hit or miss:
                row += (f"  cache hit={int(hit)} "
                        f"compile={int(miss)}")
            lines.append(row)
    if states:
        fo = _counter_sum(doc, "paddle_tpu_router_failovers_total")
        rr = _counter_sum(doc, "paddle_tpu_router_reroutes_total")
        totals = f"  failovers={int(fo)}  reroutes={int(rr)}"
        shed = _series(doc, "paddle_tpu_router_shed_total")
        if any(s["value"] for s in shed):
            totals += "  shed: " + " ".join(
                f"{s['labels']['reason']}={int(s['value'])}"
                for s in shed if s["value"])
        lines.append(totals)
        aff = "paddle_tpu_router_affinity_tokens_total"
        ahit = _counter_sum(doc, aff, outcome="hit")
        amiss = _counter_sum(doc, aff, outcome="miss")
        if ahit + amiss:
            lines.append(
                f"  affinity     {ahit / (ahit + amiss):6.1%}  "
                f"({int(ahit)} of {int(ahit + amiss)} routed prompt "
                "tokens)")

    # roofline: achieved-vs-peak per executable family (published only
    # on devices with known peaks) + the dispatch-gap profile of the
    # eager backward engine (p95 between frames when watching live)
    roof = {}
    for s in _series(doc, "paddle_tpu_roofline_utilization"):
        if s["value"]:
            roof.setdefault(s["labels"]["family"], {})[
                s["labels"]["bound"]] = s["value"]
    gap = _hist_quantiles(doc, "paddle_tpu_dispatch_gap_seconds",
                          prev=prev)
    gc_name = "paddle_tpu_backward_graph_cache_total"
    gc = {o: _counter_sum(doc, gc_name, outcome=o)
          for o in ("hit", "miss", "bypass")}
    if roof or gap or any(gc.values()):
        lines.append("== roofline ==")
        for fam, bounds in sorted(roof.items()):
            lines.append(f"  {fam:<16} " + "  ".join(
                f"{b}={bounds[b]:6.1%}" for b in sorted(bounds)))
        if gap:
            lines.append(f"  dispatch gap   p50={_ms(gap['p50'])}  "
                         f"p95={_ms(gap['p95'])}  n={gap['count']}")
        if any(gc.values()):
            total = sum(gc.values())
            lines.append(
                f"  graph cache    hit={gc['hit'] / total:6.1%}  "
                f"({int(gc['hit'])} hit / {int(gc['miss'])} miss / "
                f"{int(gc['bypass'])} bypass backwards)")

    # training numerics: grad/param norms + update ratio from the
    # in-trace stats plane, AMP loss-scale state, nonfinite totals and
    # the divergence bundle count (present only while numerics is on)
    # zero-valued rows are obs.reset() leftovers (registered series
    # survive a reset) — filter them, the family-budget convention
    gn = {s["labels"].get("group"): s["value"]
          for s in _series(doc, "paddle_tpu_train_grad_norm")
          if s["value"]}
    scale = _value(doc, "paddle_tpu_amp_loss_scale") or None
    nonf = {s["labels"]["where"]: int(s["value"])
            for s in _series(doc, "paddle_tpu_train_nonfinite_total")}
    if gn or scale is not None or any(nonf.values()):
        lines.append("== numerics ==")
        if gn:
            lines.append("  grad norm    " + "  ".join(
                f"{k}={gn[k]:.4g}"
                for k in sorted(gn, key=lambda k: (k != "all", k))))
        pn = _value(doc, "paddle_tpu_train_param_norm") or None
        ur = _value(doc, "paddle_tpu_train_update_ratio") or None
        if pn is not None:
            row = f"  param norm   {pn:.4g}"
            if ur is not None:
                row += f"   update ratio {ur:.3g}"
            lines.append(row)
        if scale is not None:
            ok = _counter_sum(doc, "paddle_tpu_amp_steps_total",
                              outcome="ok")
            sk = _counter_sum(doc, "paddle_tpu_amp_steps_total",
                              outcome="skipped")
            decr = _counter_sum(doc,
                                "paddle_tpu_amp_scale_decreases_total")
            lines.append(f"  loss scale   {scale:g}   steps "
                         f"ok={int(ok)} skipped={int(sk)} "
                         f"decreases={int(decr)}")
        if any(nonf.values()):
            lines.append("  nonfinite    " + "  ".join(
                f"{w}={nonf.get(w, 0)}"
                for w in ("grad", "param", "loss")))
        div = _counter_sum(doc, "paddle_tpu_flight_bundles_total",
                           reason="numerics_divergence")
        if div:
            lines.append(f"  divergence bundles {int(div)}")

    # collective telemetry: per-op latency percentiles + bytes rates,
    # goodput split, and the aggregator's cross-rank skew / straggler
    # attribution (present only in a fleet aggregator's export)
    cq = _hist_quantiles_by(doc, "paddle_tpu_collective_seconds", "op",
                            prev=prev)
    launches = _series(doc, "paddle_tpu_collective_launches_total")
    skews = [s for s in
             _series(doc, "paddle_tpu_collective_skew_seconds")
             if s["value"]]
    if cq or any(s["value"] for s in launches) or skews:
        lines.append("== comms ==")
        ops = sorted(set(cq) | {s["labels"]["op"] for s in launches
                                if s["value"]})
        for op in ops:
            q = cq.get(op)
            bps = rate("paddle_tpu_collective_bytes_total", op=op)
            calls = _counter_sum(
                doc, "paddle_tpu_collective_launches_total", op=op)
            row = f"  {op:<16} n={int(calls):>6}"
            if q:
                row += (f"  p50={_ms(q['p50'])}  "
                        f"p95={_ms(q['p95'])}")
            if bps is not None:
                row += f"  ({bps / 1e6:8.2f} MB/s)"
            lines.append(row)
        good = {s["labels"]["component"]: s["value"] for s in
                _series(doc, "paddle_tpu_train_goodput_fraction")}
        if good:
            lines.append("  goodput      " + "  ".join(
                f"{k}={good[k]:6.1%}" for k in
                ("compute", "comms", "stall") if k in good))
        stragglers = {
            s["labels"]["op"]: s["labels"]["process"] for s in
            _series(doc, "paddle_tpu_collective_straggler")
            if s["value"]}
        for s in sorted(skews, key=lambda s: s["labels"]["op"]):
            op = s["labels"]["op"]
            row = f"  skew         {op}={s['value']:.3f}s"
            if op in stragglers:
                row += f"  straggler={stragglers[op]}"
            lines.append(row)

    # embedding: the terabyte-table plane (README "Terabyte-scale
    # embeddings") — lookup/update latency, tier hit rate, exchange
    emb_rows = _series(doc, "paddle_tpu_embedding_rows_total")
    if any(s["value"] for s in emb_rows):
        lines.append("== embedding ==")
        for op in ("lookup", "update"):
            n = _counter_sum(doc, "paddle_tpu_embedding_rows_total",
                             op=op)
            q = _hist_quantiles(
                doc, f"paddle_tpu_embedding_{op}_seconds", prev=prev)
            rps = rate("paddle_tpu_embedding_rows_total", op=op)
            row = f"  {op:<9} rows={int(n):>10}"
            if q:
                row += f"  p50={_ms(q['p50'])}  p95={_ms(q['p95'])}"
            if rps is not None:
                row += f"  ({rps:10.1f} rows/s)"
            lines.append(row)
        hot = _counter_sum(doc, "paddle_tpu_embedding_tier_rows_total",
                           tier="hot")
        cold = _counter_sum(
            doc, "paddle_tpu_embedding_tier_rows_total", tier="cold")
        if hot + cold:
            ev = _counter_sum(doc,
                              "paddle_tpu_embedding_evictions_total")
            lines.append(
                f"  tier      hit={hot / (hot + cold):6.1%}  "
                f"hot={int(hot)}  cold={int(cold)}  "
                f"evictions={int(ev)}")
        xb = {s["labels"]["payload"]: s["value"] for s in _series(
            doc, "paddle_tpu_embedding_exchange_bytes_total")}
        if xb:
            pad = _value(doc,
                         "paddle_tpu_embedding_exchange_pad_fraction")
            row = "  exchange  " + "  ".join(
                f"{p}={xb[p] / 1e6:.2f}MB" for p in
                ("ids", "rows", "grads") if p in xb)
            if pad is not None:
                row += f"  pad={pad:6.1%}"
            lines.append(row)
        pf = {s["labels"]["outcome"]: s["value"] for s in _series(
            doc, "paddle_tpu_embedding_prefetch_total")}
        if pf:
            lines.append("  prefetch  " + "  ".join(
                f"{k}={int(pf[k])}" for k in
                ("hit", "stale", "invalidated") if k in pf))
        logical = _value(doc, "paddle_tpu_embedding_logical_bytes")
        if logical is not None:
            resident = _value(
                doc, "paddle_tpu_embedding_resident_bytes") or 0
            disk = _value(doc, "paddle_tpu_embedding_disk_bytes") or 0
            lines.append(
                f"  bytes     logical={logical / 1e6:.1f}MB  "
                f"resident={resident / 1e6:.1f}MB  "
                f"disk={disk / 1e6:.1f}MB")

    comp = _series(doc, "paddle_tpu_compile_total")
    if comp:
        lines.append("== compiles ==")
        fams = {}
        for s in comp:
            lbl = s["labels"]
            slot = fams.setdefault(lbl["family"], {})
            out = lbl.get("outcome", "compile")
            slot[out] = slot.get(out, 0.0) + s["value"]
        for fam in sorted(fams):
            slot = fams[fam]
            row = f"  {fam:<20} {int(sum(slot.values())):>4}"
            if slot.get("disk_hit"):
                row += f"  (disk_hit={int(slot['disk_hit'])})"
            lines.append(row)

    hbm_pool = _series(doc, "paddle_tpu_hbm_page_pool_bytes")
    hbm_live = _value(doc, "paddle_tpu_hbm_live_array_bytes")
    if hbm_pool or hbm_live is not None:
        lines.append("== hbm ==")
        for s in hbm_pool:
            lines.append(f"  pool {s['labels']['state']:<9} "
                         f"{s['value'] / 1e6:10.2f} MB")
        if hbm_live is not None:
            lines.append(f"  live arrays    {hbm_live / 1e6:10.2f} MB")

    # autopilot: closed-loop remediation accounting (present only in
    # an aggregator/supervisor export — see README "Training autopilot")
    eps = _series(doc, "paddle_tpu_autopilot_episodes_total")
    open_eps = _value(doc, "paddle_tpu_autopilot_open_episodes")
    if eps or open_eps:
        lines.append("== autopilot ==")
        if open_eps:
            lines.append(f"  open episodes  {int(open_eps)}")
        for s in sorted(eps, key=lambda s: (s["labels"]["kind"],
                                            s["labels"]["outcome"])):
            if s["value"]:
                lines.append(f"  {s['labels']['kind']:<12} "
                             f"{s['labels']['outcome']:<11} "
                             f"{int(s['value']):>4}")
        last = [s["labels"]["action"] for s in
                _series(doc, "paddle_tpu_autopilot_last_action")
                if s["value"]]
        acts = {s["labels"]["action"]: int(s["value"]) for s in
                _series(doc, "paddle_tpu_autopilot_actions_total")
                if s["value"]}
        if acts:
            row = "  actions      " + "  ".join(
                f"{a}={n}" for a, n in sorted(acts.items()))
            if last:
                row += f"   last={last[0]}"
            lines.append(row)
        fails = _counter_sum(
            doc, "paddle_tpu_autopilot_action_failures_total")
        if fails:
            lines.append(f"  action failures {int(fails)} "
                         "(journaled; retried next scan)")
        det = _hist_quantiles(
            doc, "paddle_tpu_autopilot_detection_latency_seconds",
            prev=prev)
        mttr = _hist_quantiles(
            doc, "paddle_tpu_autopilot_mttr_seconds", prev=prev)
        if det:
            lines.append(f"  detection    p50={_ms(det['p50'])}  "
                         f"p95={_ms(det['p95'])}")
        if mttr:
            lines.append(f"  mttr         p50={_ms(mttr['p50'])}  "
                         f"p95={_ms(mttr['p95'])}")

    # slo: serving SLO control plane — fleet SLO verdicts, TTFT budget
    # attribution, autoscaler state (README "Serving SLO control
    # plane"); present only where a FleetSLOMonitor/Autoscaler runs
    att = _series(doc, "paddle_tpu_slo_attained_fraction")
    bud = _series(doc, "paddle_tpu_request_ttft_budget_seconds")
    asc_n = _value(doc, "paddle_tpu_autoscaler_replicas")
    if att or bud or asc_n is not None:
        lines.append("== slo ==")
        for s in sorted(att, key=lambda s: s["labels"]["slo"]):
            slo_name = s["labels"]["slo"]
            obj = _value(doc, "paddle_tpu_slo_objective_fraction",
                         slo=slo_name)
            ok = obj is None or s["value"] >= obj
            breaches = _counter_sum(
                doc, "paddle_tpu_slo_breaches_total", slo=slo_name)
            row = (f"  {slo_name:<14} attained {s['value'] * 100:6.2f}%"
                   f"  objective {(obj or 0.0) * 100:6.2f}%  "
                   f"{'ok' if ok else 'BREACH'}")
            if breaches:
                row += f"  (breached evals {int(breaches)})"
            lines.append(row)
        tot = sum(s["value"]["sum"] for s in bud)
        if tot > 0:
            lines.append("  ttft budget (component share of "
                         "fleet-total ttft)")
            for s in sorted(bud, key=lambda s: -s["value"]["sum"]):
                frac = s["value"]["sum"] / tot
                lines.append(f"    {s['labels']['component']:<15} "
                             f"{frac * 100:5.1f}% "
                             f"{'#' * int(round(frac * 24))}")
        if asc_n is not None:
            decs = {s["labels"]["action"]: int(s["value"]) for s in
                    _series(doc, "paddle_tpu_autoscaler_decisions_total")
                    if s["value"]}
            last = [s["labels"]["action"] for s in
                    _series(doc, "paddle_tpu_autoscaler_last_decision")
                    if s["value"]]
            row = f"  autoscaler   replicas={int(asc_n)}"
            if decs:
                row += "  " + "  ".join(
                    f"{a}={n}" for a, n in sorted(decs.items()))
            if last:
                row += f"   last={last[0]}"
            lines.append(row)

    # disagg: prefill/decode disaggregation — role pool sizes, handoff
    # path split, migration throughput, per-role request latency
    # (README "Prefill/decode disaggregation")
    pools = {s["labels"]["role"]: int(s["value"]) for s in
             _series(doc, "paddle_tpu_disagg_pool_replicas")}
    hand = {s["labels"]["path"]: int(s["value"]) for s in
            _series(doc, "paddle_tpu_disagg_handoffs_total")
            if s["value"]}
    if any(pools.values()) or hand:
        lines.append("== disagg ==")
        if pools:
            lines.append("  pools        " + "  ".join(
                f"{role}={pools[role]}" for role in sorted(pools)))
        if hand:
            lines.append("  handoffs     " + "  ".join(
                f"{p}={n}" for p, n in sorted(hand.items())))
        mig = _counter_sum(doc,
                           "paddle_tpu_disagg_migrated_bytes_total")
        if mig:
            mbs = rate("paddle_tpu_disagg_migrated_bytes_total")
            row = f"  migrated     {mig / 1e6:10.2f} MB"
            if mbs is not None:
                row += f"  ({mbs / 1e6:8.2f} MB/s)"
            lines.append(row)
        hq = _hist_quantiles(doc, "paddle_tpu_disagg_handoff_seconds",
                             prev=prev)
        if hq:
            lines.append(f"  handoff      p50={_ms(hq['p50'])}  "
                         f"p95={_ms(hq['p95'])}  n={hq['count']}")
        # per-role TTFT/TPOT from a fleet-merged doc: a process maps
        # to its pool via the pid join series' role label, falling
        # back to the launcher's role-in-name convention
        # ("disagg-prefill-0")
        role_of = {s["labels"]["process"]: s["labels"].get("role", "")
                   for s in _series(doc,
                                    "paddle_tpu_fleet_process_pid")}
        for label, name in (
                ("TTFT", "paddle_tpu_request_ttft_seconds"),
                ("TPOT", "paddle_tpu_request_tpot_seconds")):
            rec = doc.get(name)
            if not rec or rec.get("kind") != "histogram":
                continue
            for role in ("prefill", "decode"):
                counts = None
                for s in rec["series"]:
                    proc = s["labels"].get("process")
                    if proc is None or \
                            role not in (role_of.get(proc) or proc):
                        continue
                    b = s["value"]["buckets"]
                    counts = b if counts is None else \
                        [x + y for x, y in zip(counts, b)]
                if counts and sum(counts):
                    p95 = quantile_from_buckets(
                        rec["buckets"], counts, 0.95)
                    lines.append(
                        f"  {label} {role:<8} p95={_ms(p95)}  "
                        f"n={int(sum(counts))}")

    fl = _series(doc, "paddle_tpu_flight_bundles_total")
    if fl:
        lines.append("== flight bundles ==")
        for s in fl:
            lines.append(f"  {s['labels']['reason']:<16} "
                         f"{int(s['value']):>4}")

    fleet = render_fleet(doc, prev, dt)
    if fleet:
        lines.append(fleet)
    return "\n".join(lines)


def _load(args):
    if args.json:
        with open(args.json) as f:
            return json.load(f)
    if args.bundle:
        with open(os.path.join(args.bundle, "metrics.json")) as f:
            return json.load(f)
    from paddle_tpu import observability as obs
    return json.loads(obs.to_json())


def _run_demo():
    """Tiny synthetic workload so --demo has numbers to show."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import observability as obs
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import gpt_tiny

    obs.enable()
    obs.reset()
    pt.seed(0)
    model = GPTForCausalLM(gpt_tiny())
    model.eval()
    rng = np.random.default_rng(0)
    eng = LLMEngine(model, max_batch=2, block_size=16, decode_chunk=4,
                    prompt_quantum=16, max_model_len=64)
    prompts = [rng.integers(0, 1024, (int(n),)).astype(np.int32)
               for n in rng.integers(4, 20, 6)]
    eng.generate(prompts, max_new_tokens=10)


def main():
    ap = argparse.ArgumentParser()
    src = ap.add_mutually_exclusive_group()
    src.add_argument("--json", help="obs.to_json() export file")
    src.add_argument("--bundle", help="flight-recorder bundle dir")
    src.add_argument("--demo", action="store_true",
                     help="run a synthetic workload, render one frame")
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit")
    ap.add_argument("--fleet", action="store_true",
                    help="render only the fleet panel (point --json at "
                         "a FleetAggregator.export_json file for a "
                         "live per-process heartbeat/inflight/capacity "
                         "view)")
    ap.add_argument("--interval", type=float, default=2.0)
    args = ap.parse_args()
    if not (args.json or args.bundle or args.demo):
        ap.error("pick a source: --json FILE, --bundle DIR or --demo")

    if args.demo:
        _run_demo()
    renderer = render_fleet if args.fleet else render
    prev = t_prev = None
    while True:
        doc = _load(args)
        now = time.perf_counter()
        frame = renderer(doc, prev,
                         None if t_prev is None else now - t_prev)
        if args.once or args.demo:
            print(frame)
            return 0
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        prev, t_prev = doc, now
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
