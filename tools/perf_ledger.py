"""Perf-ledger trajectory and regression ATTRIBUTION over the
per-family expected/achieved records `bench.py` appends to
`perf_ledger.jsonl` (observability.perf.family_records, one record per
config run).

The round-over-round gate (`bench.py --gate`) answers "did throughput
regress"; this tool answers "WHICH executable family regressed": it
diffs the latest record per config against the ledger history,
comparing each family's achieved bytes/s (the HBM-bound side — every
hot path in this repo is bandwidth-dominated, see BENCH_EXTRA).

    python tools/perf_ledger.py                  # trajectory table
    python tools/perf_ledger.py --check          # diff latest vs history
    python tools/perf_ledger.py --check --tol 0.2 --config decode_paged

`--check` verdict rules (printed as one JSON line, exit 0/1):
  * a family whose achieved rate dropped below (1 - tol) x the best
    PRIOR-REVISION record for the same config FAILS and names the
    family — the attribution the gate cannot give;
  * prior records from the SAME revision only report the ratio (two
    runs of one revision differ by box noise, not by code — the
    interleaved-window gate is the honest same-code comparator, cf.
    the BENCH_EXTRA methodology findings), so a ledger written
    entirely by the current revision is self-consistent and passes;
  * a family present in every prior record of a config but MISSING
    from the latest fails (an instrumented path silently stopped
    running — the regression observability itself would otherwise
    hide);
  * records carrying a backward dispatch `mode` (bench.py --config
    dispatch writes one per mode: per_node, batched, whole_graph) are
    baselined per (config, mode), and their
    `dispatch_gap.ms_per_step` is checked the same way bytes/s is — a
    latest gap total ABOVE (1 + tol) x the best prior-revision record
    for the same (config, mode) fails, so the fused engines' host-gap
    win cannot silently erode; a whole_graph record's `graph_cache`
    hit/miss/bypass counts ride the record and are echoed in the
    verdict (report-only: steady-state O(1) dispatch shows as hits
    dominating);
  * the dispatch config's whole_graph record also carries the
    training-numerics on-vs-off overhead ratio (`numerics.
    overhead_ratio`, bench.py --config dispatch) — a cost like the
    gap total, checked with the same mirror rule plus an absolute
    floor, so the numerics plane's ≤3% overhead claim cannot silently
    erode; the measured grad norm rides report-only;
  * records carrying a fleet `process_role` (observability.fleet's
    `append_capacity_ledger` writes one per process) are baselined per
    (config, process_role), and their `capacity.req_per_s` /
    `capacity.tok_per_s` follow the bytes/s rule — a role's achieved
    rate dropping below (1 - tol) x the best prior-revision record
    fails, naming the role the elastic scaler is about to mis-size
    from;
  * the router_serving record's `reintegration` block (bench.py's
    cold-vs-warm process-fleet phase over the persistent executable
    store) is a cost mirror: `warm_over_cold` rising above (1 + tol)
    x the best prior-revision ratio AND past an absolute floor fails,
    and `warm_skipped_all_compiles=false` fails outright — a warm
    replacement re-compiling executables it should have disk-loaded
    is the store not working, not a slow box.

Records keep absolute achieved rates, so cross-revision diffs carry
the same box-noise caveat as any non-interleaved comparison — the
verdict names suspects for the gate to re-measure, it does not replace
the gate."""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def default_ledger_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "perf_ledger.jsonl")


def load(path: str):
    """[(lineno, record)] in file order; malformed lines are counted,
    not fatal (a crashed bench append must not wedge the tool)."""
    records, bad = [], 0
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                if isinstance(rec, dict) and "families" in rec:
                    records.append((i, rec))
                else:
                    bad += 1
            except ValueError:
                bad += 1
    return records, bad


def _achieved(fam_rec) -> float:
    v = fam_rec.get("achieved_bytes_per_s")
    return float(v) if v else 0.0


def _config_key(rec) -> str:
    """Baseline grouping key: config, suffixed with the backward
    dispatch mode when present — batched and per_node records of the
    dispatch config baseline independently — and with the fleet
    process_role when present (observability.fleet capacity records:
    prefill replicas and decode replicas of one fleet config baseline
    independently, the way dispatch modes do)."""
    config = rec.get("config", "?")
    mode = rec.get("mode")
    role = rec.get("process_role")
    # a DISPLAY label, not an executable-cache key: all components
    # are strings straight from the record, no coercion to hide
    key = f"{config}[{mode}]" if mode else config  # graftlint: disable=unstable-cache-key
    return f"{key}@{role}" if role else key  # graftlint: disable=unstable-cache-key


# a gap delta below this is timer jitter, not a regression — it gives
# the dispatch-gap check a finite threshold even over a 0.0 baseline
GAP_FLOOR_MS_PER_STEP = 0.01

# numerics on-vs-off overhead is a ratio near 1.0 measured on a noisy
# box: require the regression to clear an absolute floor on top of the
# relative tolerance (the GAP_FLOOR idiom) before failing
NUMERICS_OVERHEAD_FLOOR = 0.05


def _numerics_ratio(rec):
    num = rec.get("numerics")
    if not isinstance(num, dict):
        return None
    v = num.get("overhead_ratio")
    return float(v) if v is not None else None


# warm/cold fleet-reintegration is a wall-clock ratio on a noisy box
# (process spawn + RPC + deserialize over a spawn + RPC + compile
# baseline): require the regression to clear an absolute floor on top
# of the relative tolerance, the NUMERICS_OVERHEAD_FLOOR idiom
REINTEGRATION_FLOOR_RATIO = 0.05


def _reint_ratio(rec):
    reint = rec.get("reintegration")
    if not isinstance(reint, dict):
        return None
    v = reint.get("warm_over_cold")
    return float(v) if v is not None else None


def _gap_ms(rec):
    gap = rec.get("dispatch_gap")
    if not isinstance(gap, dict):
        return None
    v = gap.get("ms_per_step")
    return float(v) if v is not None else None


def check(records, tol: float, only_config=None) -> dict:
    """Diff the LATEST record per (config, mode) against that group's
    ledger history. Returns the verdict dict (see module docstring)."""
    by_config = {}
    for _ln, rec in records:
        by_config.setdefault(_config_key(rec), []).append(rec)
    verdict = {"pass": True, "tol": tol, "configs": {}}
    for config, recs in sorted(by_config.items()):
        if only_config and config.split("[", 1)[0].split("@", 1)[0] \
                != only_config:
            continue
        latest = recs[-1]
        # baselines must share the latest record's DEVICE: achieved
        # rates are absolute, and a v5e record is not a regression
        # baseline for a CPU smoke run of the same config
        history = [r for r in recs[:-1]
                   if r.get("device") == latest.get("device")]
        out = {"rev": latest.get("rev"), "history": len(history),
               "families": {}, "missing_families": [], "pass": True}
        for family, fam_rec in sorted(latest["families"].items()):
            cur = _achieved(fam_rec)
            fout = {"achieved_bytes_per_s": cur or None,
                    "ratio_vs_history": None, "baseline_rev": None,
                    "regressed": False}
            # baseline: best prior achieved rate, preferring a
            # DIFFERENT revision (same-rev deltas are box noise)
            prior = [(_achieved(pf), prev.get("rev"))
                     for prev in history
                     for pf in [prev["families"].get(family)]
                     if pf and _achieved(pf)]
            other_rev = [p for p in prior if p[1] != latest.get("rev")]
            best, best_rev = max(other_rev or prior,
                                 default=(None, None))
            if best and cur:
                fout["ratio_vs_history"] = round(cur / best, 4)
                fout["baseline_rev"] = best_rev
                if best_rev != latest.get("rev") \
                        and cur / best < 1.0 - tol:
                    fout["regressed"] = True
                    out["pass"] = False
            out["families"][family] = fout
        if history:
            always = set(history[0]["families"])
            for prev in history[1:]:
                always &= set(prev["families"])
            gone = sorted(always - set(latest["families"]))
            if gone:
                out["missing_families"] = gone
                out["pass"] = False
        # dispatch-gap regression: the gap total is a COST, so the
        # mirror of the bytes/s rule — latest above (1 + tol) x the
        # best (lowest) prior-revision gap for this (config, mode)
        # fails; same-rev priors report-only, same-device only. An
        # absolute floor keeps a 0.0 baseline (the routine batched
        # result: one fused dispatch per backward, zero gaps) from
        # giving the check infinite sensitivity to timer jitter.
        cur_gap = _gap_ms(latest)
        if cur_gap is not None:
            gout = {"ms_per_step": cur_gap, "ratio_vs_history": None,
                    "baseline_rev": None, "regressed": False}
            prior = [(_gap_ms(prev), prev.get("rev"))
                     for prev in history]
            prior = [p for p in prior if p[0] is not None]
            other_rev = [p for p in prior if p[1] != latest.get("rev")]
            pool = other_rev or prior
            if pool:
                best_gap, best_rev = min(pool)
                if best_gap > 0:
                    gout["ratio_vs_history"] = round(
                        cur_gap / best_gap, 4)
                gout["baseline_rev"] = best_rev
                if best_rev != latest.get("rev") and cur_gap > max(
                        best_gap * (1.0 + tol),
                        best_gap + GAP_FLOOR_MS_PER_STEP):
                    gout["regressed"] = True
                    out["pass"] = False
            out["dispatch_gap"] = gout
        # whole-graph trace-cache counts ride along report-only: the
        # steady-state claim (hits dominate) is pinned by tests; here
        # the verdict just keeps the observability next to the gap it
        # explains
        gc = latest.get("graph_cache")
        if isinstance(gc, dict):
            out["graph_cache"] = gc
        # numerics-plane overhead regression (ISSUE 15): the dispatch
        # config's whole_graph record carries the measured numerics
        # on-vs-off step-time ratio — a COST like the gap total, so
        # the same mirror rule: latest above (1 + tol) x the best
        # (lowest) prior-revision ratio AND past an absolute floor
        # fails; same-rev priors report-only, same-device only.
        cur_num = _numerics_ratio(latest)
        if cur_num is not None:
            nout = {"overhead_ratio": cur_num,
                    "ratio_vs_history": None, "baseline_rev": None,
                    "regressed": False,
                    "grad_norm": (latest.get("numerics") or {}).get(
                        "grad_norm")}
            prior = [(_numerics_ratio(prev), prev.get("rev"))
                     for prev in history]
            prior = [p for p in prior if p[0] is not None]
            other_rev = [p for p in prior if p[1] != latest.get("rev")]
            pool = other_rev or prior
            if pool:
                best_num, best_rev = min(pool)
                if best_num > 0:
                    nout["ratio_vs_history"] = round(
                        cur_num / best_num, 4)
                nout["baseline_rev"] = best_rev
                if best_rev != latest.get("rev") and cur_num > max(
                        best_num * (1.0 + tol),
                        best_num + NUMERICS_OVERHEAD_FLOOR):
                    nout["regressed"] = True
                    out["pass"] = False
            out["numerics"] = nout
        # fleet warm-reintegration regression (router_serving's
        # process-fleet phase): warm_over_cold is the fraction of a
        # cold fleet bring-up a WARM replacement still pays — a COST,
        # so the gap/numerics mirror rule: latest above (1 + tol) x
        # the best (lowest) prior-revision ratio AND past an absolute
        # floor fails. A warm pass that re-compiled anything it
        # should have disk-loaded (warm_skipped_all_compiles false)
        # fails outright — that is the persistent store silently not
        # working, not a slow box.
        cur_reint = _reint_ratio(latest)
        if cur_reint is not None:
            reint = latest.get("reintegration") or {}
            rout = {"warm_over_cold": cur_reint,
                    "cold_s": reint.get("cold_s"),
                    "warm_s": reint.get("warm_s"),
                    "warm_skipped_all_compiles":
                        reint.get("warm_skipped_all_compiles"),
                    "ratio_vs_history": None, "baseline_rev": None,
                    "regressed": False}
            if reint.get("warm_skipped_all_compiles") is False:
                rout["regressed"] = True
                out["pass"] = False
            prior = [(_reint_ratio(prev), prev.get("rev"))
                     for prev in history]
            prior = [p for p in prior if p[0] is not None]
            other_rev = [p for p in prior if p[1] != latest.get("rev")]
            pool = other_rev or prior
            if pool:
                best_r, best_rev = min(pool)
                if best_r > 0:
                    rout["ratio_vs_history"] = round(
                        cur_reint / best_r, 4)
                rout["baseline_rev"] = best_rev
                if best_rev != latest.get("rev") and cur_reint > max(
                        best_r * (1.0 + tol),
                        best_r + REINTEGRATION_FLOOR_RATIO):
                    rout["regressed"] = True
                    out["pass"] = False
            out["reintegration"] = rout
        # fleet capacity regression: achieved rates are the bytes/s
        # rule again — the latest record's req/s / tok/s below
        # (1 - tol) x the best prior-revision record for the same
        # (config, process_role) fails, so a fleet role cannot quietly
        # lose capacity between revisions (the elastic scaler sizes
        # fleets from these numbers). Same-rev priors report-only,
        # same-device only, like every other check here.
        cap = latest.get("capacity")
        if isinstance(cap, dict):
            out["capacity"] = {}
            for rate_key in ("req_per_s", "tok_per_s"):
                cur_rate = cap.get(rate_key)
                rout = {"value": cur_rate, "ratio_vs_history": None,
                        "baseline_rev": None, "regressed": False}
                prior = [(prev.get("capacity", {}).get(rate_key),
                          prev.get("rev")) for prev in history
                         if isinstance(prev.get("capacity"), dict)]
                prior = [p for p in prior if p[0]]
                other_rev = [p for p in prior
                             if p[1] != latest.get("rev")]
                pool = other_rev or prior
                if pool and cur_rate:
                    best, best_rev = max(pool)
                    rout["ratio_vs_history"] = round(cur_rate / best, 4)
                    rout["baseline_rev"] = best_rev
                    if best_rev != latest.get("rev") \
                            and cur_rate / best < 1.0 - tol:
                        rout["regressed"] = True
                        out["pass"] = False
                out["capacity"][rate_key] = rout
        verdict["configs"][config] = out
        verdict["pass"] = verdict["pass"] and out["pass"]
    if only_config and not verdict["configs"]:
        verdict["pass"] = False
        verdict["error"] = f"no ledger records for config {only_config!r}"
    return verdict


def trajectory(records) -> str:
    """Human table: one line per (record, family) in ledger order,
    plus a gap line per record carrying a dispatch_gap and a sweep
    line per recorded autotune sweep."""
    lines = [f"{'config':<22} {'rev':<19} {'family':<16} "
             f"{'runs':>5} {'GB/s':>9} {'util_hbm':>9} {'util_flops':>10}"]
    for _ln, rec in records:
        ckey = _config_key(rec)
        for family, f in sorted(rec["families"].items()):
            bps = f.get("achieved_bytes_per_s")
            uh, uf = f.get("utilization_hbm"), f.get("utilization_flops")
            lines.append(
                f"{ckey:<22} {rec.get('rev', '?'):<19} "
                f"{family:<16} {f.get('runs', 0):>5} "
                f"{'-' if not bps else f'{bps / 1e9:9.3f}':>9} "
                f"{'-' if uh is None else f'{uh:9.4f}':>9} "
                f"{'-' if uf is None else f'{uf:10.4f}':>10}")
        gap = _gap_ms(rec)
        if gap is not None:
            lines.append(f"{ckey:<22} {rec.get('rev', '?'):<19} "
                         f"{'(dispatch gap)':<16} "
                         f"{gap:9.4f} ms/step")
        gc = rec.get("graph_cache")
        if isinstance(gc, dict):
            lines.append(
                f"{ckey:<22} {rec.get('rev', '?'):<19} "
                f"{'(graph cache)':<16} "
                + " ".join(f"{k}={gc.get(k, 0)}"
                           for k in ("hit", "miss", "bypass")))
        nr = _numerics_ratio(rec)
        if nr is not None:
            gnorm = (rec.get("numerics") or {}).get("grad_norm")
            lines.append(
                f"{ckey:<22} {rec.get('rev', '?'):<19} "
                f"{'(numerics)':<16} "
                f"overhead=x{nr:.4f}"
                + (f" grad_norm={gnorm:.4g}" if gnorm is not None
                   else ""))
        rr = _reint_ratio(rec)
        if rr is not None:
            reint = rec.get("reintegration") or {}
            lines.append(
                f"{ckey:<22} {rec.get('rev', '?'):<19} "
                f"{'(reintegration)':<16} "
                f"warm/cold=x{rr:.4f} "
                f"cold={reint.get('cold_s', '-')}s "
                f"warm={reint.get('warm_s', '-')}s "
                f"all_disk_hits={reint.get('warm_skipped_all_compiles')}")
        cap = rec.get("capacity")
        if isinstance(cap, dict):
            req, tok = cap.get("req_per_s"), cap.get("tok_per_s")
            lines.append(
                f"{ckey:<22} {rec.get('rev', '?'):<19} "
                f"{'(capacity)':<16} "
                f"req/s={'-' if req is None else f'{req:.3f}'} "
                f"tok/s={'-' if tok is None else f'{tok:.1f}'} "
                f"window={cap.get('window_s', '-')}s")
        for sw in rec.get("autotune_sweeps", ()):
            lines.append(
                f"{ckey:<22} {rec.get('rev', '?'):<19} (autotune "
                f"{'|'.join(str(p) for p in sw.get('key', []))}: "
                f"winner={tuple(sw.get('winner', ()))} "
                f"validated={sw.get('window_validated')} "
                f"persisted={sw.get('persisted')})")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="perf-ledger trajectory / per-family regression "
                    "attribution")
    ap.add_argument("--ledger", default=default_ledger_path())
    ap.add_argument("--check", action="store_true",
                    help="diff the latest record per config against "
                         "ledger history; exit 1 on an attributed "
                         "regression or a disappeared family")
    ap.add_argument("--config", default=None,
                    help="restrict --check to one bench config")
    ap.add_argument("--tol", type=float, default=0.2,
                    help="--check fails a family below (1 - tol) x its "
                         "best prior-revision rate")
    args = ap.parse_args(argv)

    if not os.path.exists(args.ledger):
        print(json.dumps({"pass": False,
                          "error": f"no ledger at {args.ledger} — run "
                                   "bench.py (without --no-ledger) "
                                   "first"}))
        return 2
    records, bad = load(args.ledger)
    if not records:
        print(json.dumps({"pass": False, "malformed_lines": bad,
                          "error": "ledger holds no usable records"}))
        return 2
    if args.check:
        verdict = check(records, args.tol, args.config)
        if bad:
            verdict["malformed_lines"] = bad
        print(json.dumps(verdict, sort_keys=True))
        return 0 if verdict["pass"] else 1
    print(trajectory(records))
    return 0


if __name__ == "__main__":
    sys.exit(main())
