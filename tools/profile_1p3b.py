"""Decompose the 1.3B training step + sweep remat variants (VERDICT r4
next-1: name where the time goes, then close the MFU gap).

Usage (one variant per process so HBM state never carries over):
    python tools/profile_1p3b.py step --policy full --batch 4
    python tools/profile_1p3b.py step --policy dots --batch 4
    python tools/profile_1p3b.py step --policy full --interval 2
    python tools/profile_1p3b.py parts          # fwd / fwd+bwd / opt split
    python tools/profile_1p3b.py micro          # flash + matmul + head/CE

Each prints one JSON line; tools/sweep_1p3b.sh drives the full sweep.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _cfg(batch, seq, policy, interval, flash=True):
    from paddle_tpu.models.gpt import GPTConfig
    return GPTConfig(
        vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16,
        max_position_embeddings=seq, hidden_dropout_prob=0.0,
        attention_dropout_prob=0.0, use_flash_attention=flash,
        recompute=policy != "none", recompute_policy=policy
        if policy != "none" else "full", recompute_interval=interval)


def _build(cfg, moment_dtype="bfloat16"):
    from paddle_tpu import amp
    from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer import AdamW

    model = GPTForCausalLM(cfg)
    model.train()
    opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                weight_decay=0.01, moment_dtype=moment_dtype)
    crit = GPTPretrainingCriterion()

    def loss_fn(m, ids, labels):
        with amp.auto_cast(enable=True, level="O1", dtype="bfloat16"):
            logits = m(ids)
        return crit(logits, labels)

    return model, opt, TrainStep(model, opt, loss_fn)


def _time(fn, steps=5, windows=2):
    fn()
    out = fn()
    np.asarray(out)
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn()
        np.asarray(out)
        best = min(best, time.perf_counter() - t0)
    return best / steps


def cmd_step(args):
    import jax
    from paddle_tpu.models.gpt import num_params
    from bench import peak_flops

    cfg = _cfg(args.batch, args.seq, args.policy, args.interval)
    model, opt, step = _build(cfg)
    rng = np.random.default_rng(0)
    ids = jax.device_put(rng.integers(
        0, cfg.vocab_size, (args.batch, args.seq)).astype(np.int32))
    labels = jax.device_put(rng.integers(
        0, cfg.vocab_size, (args.batch, args.seq)).astype(np.int32))
    dt = _time(lambda: step(ids, labels).numpy(), steps=args.steps)
    tok_s = args.batch * args.seq / dt
    n = num_params(cfg)
    mfu = 6.0 * n * tok_s / peak_flops(jax.devices()[0])
    print(json.dumps({
        "variant": f"policy={args.policy},interval={args.interval},"
                   f"b={args.batch}",
        "step_ms": round(dt * 1e3, 1), "tokens_per_sec": round(tok_s, 1),
        "mfu": round(mfu, 4)}), flush=True)


def cmd_parts(args):
    """Split: fwd-only, grad-only (fwd+bwd), full step -> opt overhead."""
    import jax
    from paddle_tpu import amp
    from paddle_tpu.jit import _collect_params, _functional_params
    import paddle_tpu.autograd.tape as _tape
    from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion

    cfg = _cfg(args.batch, args.seq, args.policy, args.interval)
    model, opt, step = _build(cfg)
    crit = GPTPretrainingCriterion()
    _, pts, _, bts = _collect_params(model)
    tensors = pts + bts
    arrs = [t._data for t in tensors]
    rng = np.random.default_rng(0)
    ids = jax.device_put(rng.integers(
        0, cfg.vocab_size, (args.batch, args.seq)).astype(np.int32))
    labels = jax.device_put(rng.integers(
        0, cfg.vocab_size, (args.batch, args.seq)).astype(np.int32))

    def loss_of(params, ids, labels):
        with _tape.no_grad(), _functional_params(tensors, params):
            with amp.auto_cast(enable=True, level="O1", dtype="bfloat16"):
                return crit(model(ids), labels)._data

    fwd = jax.jit(loss_of)
    grad = jax.jit(lambda p, i, l: jax.grad(loss_of)(p, i, l)[0])
    t_fwd = _time(lambda: fwd(arrs, ids, labels), steps=args.steps)
    t_grad = _time(lambda: np.asarray(
        grad(arrs, ids, labels).ravel()[0]), steps=args.steps)
    t_step = _time(lambda: step(ids, labels).numpy(), steps=args.steps)
    print(json.dumps({
        "variant": f"parts policy={args.policy} b={args.batch}",
        "fwd_ms": round(t_fwd * 1e3, 1),
        "fwd_bwd_ms": round(t_grad * 1e3, 1),
        "full_step_ms": round(t_step * 1e3, 1),
        "opt_update_ms": round((t_step - t_grad) * 1e3, 1)}), flush=True)


def _scan_time(body, init, iters=10):
    """Time `body` by scanning it `iters` times INSIDE one executable
    and syncing with a real D2H fetch. This backend's tunnel runtime
    (a) deduplicates repeated identical calls and (b) returns early
    from block_until_ready — so only device-side loops with data
    dependence plus .numpy()-style syncs measure truth."""
    import jax

    f = jax.jit(lambda c: jax.lax.scan(
        lambda c_, _: (body(c_), None), c, None, length=iters)[0])

    def sync(r):
        leaf = jax.tree_util.tree_leaves(r)[0]
        np.asarray(leaf.reshape(-1)[0])

    r = f(init)
    sync(r)
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        r = f(r)
        sync(r)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def cmd_micro(args):
    """Component microbenches at the 1.3B shapes."""
    import jax
    import jax.numpy as jnp
    from bench import peak_flops
    dev = jax.devices()[0]
    peak = peak_flops(dev)
    b, s, h, H, D, v = args.batch, args.seq, 2048, 16, 128, 50304
    key = jax.random.PRNGKey(0)
    out = {}

    # flash attention fwd and fwd+bwd (carry the output forward so each
    # iteration has fresh content)
    from paddle_tpu.kernels.pallas.flash_attention import flash_attention
    q = jax.random.normal(key, (b, s, H, D), jnp.bfloat16)

    t = _scan_time(lambda q: flash_attention(q, q, q, causal=True)
                   .astype(jnp.bfloat16), q)
    fl = 4.0 * b * s * s * H * D / 2  # causal halves the work
    out["flash_fwd_ms"] = round(t * 1e3, 2)
    out["flash_fwd_util"] = round(fl / t / peak, 3)

    g = jax.grad(lambda q: flash_attention(q, q, q, causal=True)
                 .astype(jnp.float32).sum())
    t = _scan_time(lambda q: (q + 1e-3 * g(q)).astype(jnp.bfloat16), q)
    out["flash_fwdbwd_ms"] = round(t * 1e3, 2)
    out["flash_fwdbwd_util"] = round(4.5 * fl / t / peak, 3)

    # the MLP-ish matmul at model shape: [b*s, h] x [h, 4h] x [4h, h]
    x = jax.random.normal(key, (b * s, h), jnp.bfloat16)
    w1 = jax.random.normal(key, (h, 4 * h), jnp.bfloat16) * 0.02
    w2 = jax.random.normal(key, (4 * h, h), jnp.bfloat16) * 0.02
    t = _scan_time(lambda x: ((x @ w1) @ w2).astype(jnp.bfloat16), x)
    out["matmul_pair_ms"] = round(t * 1e3, 2)
    out["matmul_util"] = round(2.0 * 2 * b * s * h * 4 * h / t / peak,
                               3)

    # lm head + softmax cross-entropy (the vocab-wide tail) fwd+bwd
    hid = jax.random.normal(key, (b * s, h), jnp.bfloat16)
    wv = jax.random.normal(key, (v, h), jnp.bfloat16) * 0.02
    lab = jax.random.randint(key, (b * s,), 0, v)

    def head(hid):
        logits = (hid @ wv.T).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        return (lse - jnp.take_along_axis(
            logits, lab[:, None], axis=-1)[:, 0]).mean()

    hgrad = jax.grad(head)
    t = _scan_time(lambda hid: (hid - 1e-3 * hgrad(hid)).astype(
        jnp.bfloat16), hid)
    out["head_ce_fwdbwd_ms"] = round(t * 1e3, 2)
    out["head_ce_util"] = round(4.0 * b * s * h * v / t / peak, 3)

    # optimizer-update-shaped stream: fp32 param + grad + 2 bf16 moments
    from bench import hbm_bw
    p32 = jax.random.normal(key, (n32 := 330_000_000,), jnp.float32)
    g32 = jax.random.normal(key, (n32,), jnp.float32)
    m16 = jnp.zeros((n32,), jnp.bfloat16)
    v16 = jnp.zeros((n32,), jnp.bfloat16)   # distinct buffer: both donate

    def upd(p, g, m, v_):
        m = 0.9 * m.astype(jnp.float32) + 0.1 * g
        v_ = 0.99 * v_.astype(jnp.float32) + 0.01 * g * g
        p = p - 0.001 * m / (jnp.sqrt(v_) + 1e-8)
        return p, m.astype(jnp.bfloat16), v_.astype(jnp.bfloat16)

    ju = jax.jit(upd, donate_argnums=(0, 2, 3))
    st = (p32, g32, m16, v16)

    def run():
        nonlocal st
        p, m, v_ = ju(st[0], st[1], st[2], st[3])
        st = (p, g32, m, v_)
        return p

    run()
    np.asarray(st[0][0])        # real sync; donated chain => fresh
    t0 = time.perf_counter()    # content every call (no dedup)
    for _ in range(10):
        run()
    np.asarray(st[0][0])
    t = (time.perf_counter() - t0) / 10
    bytes_ = n32 * (4 + 4 + 4 * 2 + 4)  # read p,g,m,v + write p,m,v
    out["optstream_330M_ms"] = round(t * 1e3, 2)
    out["optstream_gbps"] = round(bytes_ / t / 1e9, 1)
    out["hbm_peak_gbps"] = round(hbm_bw(dev) / 1e9, 1)
    print(json.dumps(out), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("cmd", choices=["step", "parts", "micro"])
    ap.add_argument("--policy", default="full",
                    choices=["full", "dots", "dots_no_batch", "none"])
    ap.add_argument("--interval", type=int, default=1)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()
    {"step": cmd_step, "parts": cmd_parts, "micro": cmd_micro}[args.cmd](
        args)


if __name__ == "__main__":
    main()
