"""Decompose the bs8 decode gap (VERDICT r4 next-6): where do the bytes
go? Compares the fused decode-loop executable's XLA-reported HBM
traffic against the analytic roofline (weights + KV cache once per
step), and times bs1/bs8 steps for the per-row overhead split.

    python tools/profile_decode.py            # 1.3B on the real chip
    python tools/profile_decode.py --small    # tiny config anywhere
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--steps", type=int, default=32)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import GPTConfig, num_params
    from paddle_tpu.models.generation import (_build_fused_loop,
                                              _static_cache, _family)
    from bench import hbm_bw

    if args.small:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_position_embeddings=256,
                        hidden_dropout_prob=0.0,
                        attention_dropout_prob=0.0)
        max_len, batches = 256, (1, 2)
    else:
        cfg = GPTConfig(vocab_size=50304, hidden_size=2048,
                        num_layers=24, num_heads=16,
                        max_position_embeddings=2048,
                        hidden_dropout_prob=0.0,
                        attention_dropout_prob=0.0)
        max_len, batches = 256, (1, 8)
    model = GPTForCausalLM(cfg).bfloat16()
    model.eval()
    _, fwd_fn, emb_dtype = _family(model)
    dev = jax.devices()[0]
    n = num_params(cfg)
    out = {"params": n, "scan_steps": args.steps}

    for b in batches:
        caches = _static_cache(model, b, max_len, emb_dtype)
        loop, tensors = _build_fused_loop(model, fwd_fn, False, 1.0,
                                          1.0, None, args.steps)
        params = [t._data for t in tensors]
        nxt = jnp.zeros((b,), jnp.int32)
        pos0 = jnp.asarray(128, jnp.int32)
        key = jax.random.PRNGKey(0)
        fin = jnp.zeros((b,), jnp.bool_)
        buf = jnp.zeros((b, max_len), jnp.int32)

        from paddle_tpu.observability import perf as pperf
        cm = pperf.read_cost_model(
            loop.lower(params, caches, nxt, pos0, key, fin, buf)
            .compile())
        bytes_total = cm.bytes_accessed if cm else 0.0
        bytes_step = bytes_total / args.steps

        # analytic per-step floor: all weights once (bf16) + this
        # step's cache read (+ its write-back is the same pages)
        pbytes = 2.0 * n
        cache_bytes = (2 * cfg.num_layers * cfg.num_heads * cfg.head_dim
                       * max_len * 2.0 * b)
        floor = pbytes + cache_bytes
        # time it (fresh caches each call: donation consumed them)
        def run():
            c2 = _static_cache(model, b, max_len, emb_dtype)
            b2 = jnp.zeros((b, max_len), jnp.int32)
            r = loop(params, c2, nxt, pos0, key, fin, b2)
            np.asarray(r[1])
            return r
        run()
        t0 = time.perf_counter()
        run()
        dt = time.perf_counter() - t0
        step_ms = dt / args.steps * 1e3
        out[f"bs{b}"] = {
            "xla_bytes_per_step_gb": round(bytes_step / 1e9, 3),
            "floor_bytes_per_step_gb": round(floor / 1e9, 3),
            "traffic_ratio": round(bytes_step / floor, 3),
            "step_ms_incl_cache_realloc": round(step_ms, 3),
            "roofline_step_ms": round(floor / hbm_bw(dev) * 1e3, 3),
        }
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
