"""Decompose LLMEngine serving time at 1.3B (why is a decode chunk
slower than chunk_len x the dense decode step?).

Times, with warm executables and a full batch:
  - one prefill call (sb bucket)
  - one decode-chunk executable call (host logic bypassed)
  - one engine.step() (admission + chunk + host bookkeeping)

    python tools/profile_engine.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import GPTConfig

    cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_heads=16, max_position_embeddings=2048,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    model = GPTForCausalLM(cfg).bfloat16()
    model.eval()
    rng = np.random.default_rng(0)
    eng = LLMEngine(model, max_batch=8, num_blocks=49, block_size=64,
                    decode_chunk=16, prompt_quantum=128,
                    max_model_len=2048)
    out = {}

    # fill all 8 slots with long-lived requests
    for i in range(8):
        eng.add_request(i, rng.integers(0, 50304, (100,)).astype(
            np.int32), max_new_tokens=1024)
    t0 = time.perf_counter()
    eng.step()          # admits + 8 prefills + first chunk (compiles)
    out["first_step_s"] = round(time.perf_counter() - t0, 2)

    # warm prefill timing: add one more request into a freed slot? all
    # slots busy — time the prefill fn directly on seq 0's shapes
    sb, npb_pf = 128, 2
    fn = eng._prefill_fns.get((sb, npb_pf))
    if fn is not None:
        B = eng.max_batch
        ids = np.zeros((B, sb), np.int32)
        plen = np.full((B,), 100, np.int32)
        tblp = np.full((B, npb_pf), -1, np.int32)
        for r in range(B):
            tblp[r, :2] = eng.cache.pages(r)[:2]
        params = [t._data for t in eng._tensors]

        def one_prefill(salt):
            nxt, kcs, vcs = fn(params, eng.cache.key_caches,
                               eng.cache.value_caches,
                               jnp.asarray(ids + salt),
                               jnp.asarray(plen), jnp.asarray(tblp),
                               jax.random.PRNGKey(salt))
            for i in range(eng.cache.num_layers):
                eng.cache.update(i, kcs[i], vcs[i])
            return nxt

        np.asarray(one_prefill(0))         # real sync (D2H)
        t0 = time.perf_counter()
        for i in range(4):
            np.asarray(one_prefill(i + 1))
        out["batched_prefill_ms"] = round(
            (time.perf_counter() - t0) / 4 * 1e3, 1)

    # warm chunk call, host logic included (step) vs bypassed
    t0 = time.perf_counter()
    eng.step()
    out["warm_step_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    t0 = time.perf_counter()
    for _ in range(4):
        eng.step()
    out["steady_step_ms"] = round(
        (time.perf_counter() - t0) / 4 * 1e3, 1)
    chunk = eng.decode_chunk
    out["steady_ms_per_token_row"] = round(
        out["steady_step_ms"] / chunk, 2)

    # bypass host bookkeeping: repeat the raw chunk executable
    fn = eng._decode_fns.get(chunk)
    params = [t._data for t in eng._tensors]
    B, NB = eng.max_batch, eng.cache.allocator.num_blocks
    cur = jnp.zeros((B,), jnp.int32)
    lens = jnp.asarray(np.full((B,), 200, np.int32))
    tbl = jnp.asarray(np.full((B, eng.npb_full), eng._trash_page,
                              np.int32))
    off = jnp.asarray(np.full((B, NB), -1, np.int32)
                      .__setitem__(slice(None), -1) or
                      np.full((B, NB), -1, np.int32))
    # give every row ownership of a few real blocks
    offn = np.full((B, NB), -1, np.int32)
    tbln = np.full((B, eng.npb_full), eng._trash_page, np.int32)
    for b in range(B):
        blks = [1 + (b * 5 + j) % (NB - 1) for j in range(5)]
        tbln[b, :5] = blks
        offn[b, blks] = np.arange(5) * eng.block_size
    tblj, offj = jnp.asarray(tbln), jnp.asarray(offn)
    kcs, vcs = eng.cache.key_caches, eng.cache.value_caches
    kcs, vcs, toks = fn(params, kcs, vcs, cur, lens, tblj, offj,
                        jax.random.PRNGKey(0))
    np.asarray(toks)        # real sync; donated caches differ per call
    t0 = time.perf_counter()
    for i in range(4):
        # vary cur so the dedup cache can't short-circuit the call
        kcs, vcs, toks = fn(params, kcs, vcs, cur + i, lens, tblj,
                            offj, jax.random.PRNGKey(i))
        np.asarray(toks)
    dt = (time.perf_counter() - t0) / 4
    out["raw_chunk_ms"] = round(dt * 1e3, 1)
    out["raw_ms_per_scan_step"] = round(dt / chunk * 1e3, 2)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
