"""Decompose LLMEngine serving time (why is a decode chunk slower than
chunk_len x the dense decode step?).

Times, with warm executables and a full batch:
  - one ragged packed-batch executable call (the prefill/prefix-resume/
    verify family), host logic bypassed
  - one decode-chunk executable call (host logic bypassed)
  - one engine.step() (admission + chunk + host bookkeeping)

    python tools/profile_engine.py           # 1.3B (TPU box)
    python tools/profile_engine.py --tiny    # CPU smoke shapes (the
                                             # 1.3B compile times out on
                                             # the CPU box)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import GPTConfig

    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="smoke-size model/engine (runs on the CPU box)")
    args = ap.parse_args()

    if args.tiny:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_position_embeddings=256,
                        hidden_dropout_prob=0.0,
                        attention_dropout_prob=0.0)
        eng_kw = dict(max_batch=2, num_blocks=24, block_size=16,
                      decode_chunk=4, prompt_quantum=16,
                      max_model_len=256)
        prompt_len, max_new = 20, 64
        model = GPTForCausalLM(cfg)
    else:
        cfg = GPTConfig(vocab_size=50304, hidden_size=2048,
                        num_layers=24, num_heads=16,
                        max_position_embeddings=2048,
                        hidden_dropout_prob=0.0,
                        attention_dropout_prob=0.0)
        eng_kw = dict(max_batch=8, num_blocks=49, block_size=64,
                      decode_chunk=16, prompt_quantum=128,
                      max_model_len=2048)
        prompt_len, max_new = 100, 1024
        model = GPTForCausalLM(cfg).bfloat16()
    model.eval()
    rng = np.random.default_rng(0)
    eng = LLMEngine(model, **eng_kw)
    B = eng.max_batch
    out = {"tiny": bool(args.tiny)}

    # fill all slots with long-lived requests
    for i in range(B):
        eng.add_request(i, rng.integers(0, cfg.vocab_size,
                                        (prompt_len,)).astype(np.int32),
                        max_new_tokens=max_new)
    t0 = time.perf_counter()
    eng.step()          # admits + packed prefill + first chunk (compiles)
    out["first_step_s"] = round(time.perf_counter() - t0, 2)

    # warm ragged timing: the prefill wave compiled a
    # ("ragged", token_bucket, with_pool, all_pos) executable — time it
    # directly on synthetic all-dead operands (weight stream + lm head
    # cost; the pool stream rides along when with_pool)
    rkey = next((k for k in eng._fns if k[0] == "ragged"), None)
    if rkey is not None:
        _, tb, _wp, _ap = rkey
        fn = eng._fns[rkey]
        NB = eng.cache.allocator.num_blocks
        T_pool = NB * eng.block_size
        ids = np.zeros((tb,), np.int32)
        rows = np.full((tb,), -1, np.int32)
        pos = np.zeros((tb,), np.int32)
        kvs = np.zeros((B,), np.int32)
        off = np.full((B, NB), -1, np.int32)
        wf = np.full((tb,), T_pool, np.int32)   # all writes dropped
        sel = np.zeros((B,), np.int32)
        params = [t._data for t in eng._tensors]

        def one_ragged(salt):
            nxt, kcs, vcs = fn(params, eng.cache.key_caches,
                               eng.cache.value_caches,
                               jnp.asarray(ids + salt),
                               jnp.asarray(rows), jnp.asarray(pos),
                               jnp.asarray(kvs), jnp.asarray(off),
                               jnp.asarray(wf), jnp.asarray(sel),
                               jax.random.PRNGKey(salt))
            for i in range(eng.cache.num_layers):
                eng.cache.update(i, kcs[i], vcs[i])
            return nxt

        np.asarray(one_ragged(0))          # real sync (D2H)
        t0 = time.perf_counter()
        for i in range(4):
            np.asarray(one_ragged(i + 1))
        out["ragged_tokens_bucket"] = tb
        out["ragged_launch_ms"] = round(
            (time.perf_counter() - t0) / 4 * 1e3, 1)

    # warm chunk call, host logic included (step) vs bypassed
    t0 = time.perf_counter()
    eng.step()
    out["warm_step_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    t0 = time.perf_counter()
    for _ in range(4):
        eng.step()
    out["steady_step_ms"] = round(
        (time.perf_counter() - t0) / 4 * 1e3, 1)
    chunk = eng.decode_chunk
    out["steady_ms_per_token_row"] = round(
        out["steady_step_ms"] / chunk, 2)

    # bypass host bookkeeping: repeat the raw chunk executable (the
    # post-rewire cache keys the chunked scan as ("decode", chunk))
    fn = eng._fns.get(("decode", chunk))
    if fn is None:
        # steady state may have bucketed the chunk down (headroom)
        dkey = next(k for k in eng._fns if k[0] == "decode")
        chunk = dkey[1]
        fn = eng._fns[dkey]
    params = [t._data for t in eng._tensors]
    NB = eng.cache.allocator.num_blocks
    cur = jnp.zeros((B,), jnp.int32)
    lens = jnp.asarray(np.full((B,), 2 * prompt_len, np.int32))
    # give every row ownership of a few real blocks
    offn = np.full((B, NB), -1, np.int32)
    tbln = np.full((B, eng.npb_full), eng._trash_page, np.int32)
    npages = min(5, NB - 1)
    for b in range(B):
        blks = [1 + (b * npages + j) % (NB - 1) for j in range(npages)]
        tbln[b, :npages] = blks
        offn[b, blks] = np.arange(npages) * eng.block_size
    tblj, offj = jnp.asarray(tbln), jnp.asarray(offn)
    kcs, vcs = eng.cache.key_caches, eng.cache.value_caches
    kcs, vcs, toks = fn(params, kcs, vcs, cur, lens, tblj, offj,
                        jax.random.PRNGKey(0))
    np.asarray(toks)        # real sync; donated caches differ per call
    t0 = time.perf_counter()
    for i in range(4):
        # vary cur so the dedup cache can't short-circuit the call
        kcs, vcs, toks = fn(params, kcs, vcs, cur + i, lens, tblj,
                            offj, jax.random.PRNGKey(i))
        np.asarray(toks)
    dt = (time.perf_counter() - t0) / 4
    out["raw_chunk_ms"] = round(dt * 1e3, 1)
    out["raw_ms_per_scan_step"] = round(dt / chunk * 1e3, 2)

    # per-executable cost-model expectations: every _fns entry is a
    # CompileTimed whose first (AOT) call recorded XLA's expected
    # flops/bytes — the static side of the roofline the timings above
    # are the measured side of
    out["fns"] = [
        {
            "key": "/".join(str(p) for p in key),
            "expected_gflops":
                None if fn.expected is None
                else round(fn.expected.flops / 1e9, 3),
            "expected_gb":
                None if fn.expected is None
                else round(fn.expected.bytes_accessed / 1e9, 3),
        }
        for key, fn in sorted(eng._fns.items(), key=lambda kv: str(kv[0]))
    ]
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
