#!/bin/sh
# Round-5 TPU measurement battery (one process per config).
cd "$(dirname "$0")/.."
for c in gpt1p3b resnet50 decode_paged dispatch decode; do
  echo "=== bench $c"
  timeout 1800 python bench.py --config $c 2>&1 | grep -v '^W' | tail -3
done
echo "=== micro"
timeout 1500 python tools/profile_1p3b.py micro 2>&1 | grep -v '^W' | tail -3
echo "=== parts"
timeout 1800 python tools/profile_1p3b.py parts --policy full 2>&1 | grep -v '^W' | tail -3
echo "=== 6p7b layer proxy"
timeout 1800 python tools/dryfit_6p7b.py layer 2>&1 | grep -v '^W' | tail -3
echo "=== ALL DONE"
