"""ResNet-50 HBM-traffic accounting (VERDICT r4 next-4: per-lever
numbers for the remaining roofline gap).

Measures the compiled forward's XLA-reported bytes in three modes:
  train+fast_bn_stats  — the bench configuration
  train (two-pass BN)  — what fast_bn_stats already saves
  eval                 — BN uses running stats: NO batch-stats pass;
                         the delta vs train bounds what a Pallas
                         conv+stats epilogue fusion could save

    python tools/resnet_traffic.py          # on the real chip
"""
from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def fwd_bytes(model, x, train):
    import jax
    import paddle_tpu as pt
    from paddle_tpu import amp
    from paddle_tpu.jit import _collect_params, _functional_params
    import paddle_tpu.autograd.tape as _tape

    model.train() if train else model.eval()
    _, pts_, _, bts_ = _collect_params(model)
    tensors = pts_ + bts_

    def fwd(params, xx):
        with _tape.no_grad(), _functional_params(tensors, params):
            with amp.auto_cast(enable=True, level="O1",
                               dtype="bfloat16"):
                return model(xx)._data

    from paddle_tpu.observability import perf as pperf
    cm = pperf.read_cost_model(
        jax.jit(fwd).lower([t._data for t in tensors], x).compile())
    return cm.bytes_accessed if cm else 0.0


def main():
    import paddle_tpu as pt
    from paddle_tpu.vision.models import resnet50

    batch, hw = 256, 224
    rng = np.random.default_rng(0)
    x = rng.standard_normal((batch, hw, hw, 3)).astype(np.float32)
    out = {"batch": batch}
    for name, flags, s2d in [
            ("train_fast_bn_s2d", True, True),
            ("train_fast_bn", True, False),
            ("train_twopass_bn", False, False),
            ("eval", True, False)]:
        pt.set_flags({"FLAGS_fast_bn_stats": flags})
        model = resnet50(data_format="NHWC", space_to_depth_stem=s2d)
        gb = fwd_bytes(model, x, train=not name.startswith("eval"))
        out[name + "_fwd_gb"] = round(gb / 1e9, 2)
    out["stats_pass_bound_gb"] = round(
        out["train_fast_bn_fwd_gb"] - out["eval_fwd_gb"], 2)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
