#!/bin/sh
# Remat-variant sweep + step decomposition for the 1.3B config.
# One process per variant so HBM fragmentation/donation never carries over.
cd "$(dirname "$0")/.."
for a in "micro" "parts --policy full" \
         "step --policy full" \
         "step --policy dots" \
         "step --policy dots_no_batch" \
         "step --policy full --interval 2" \
         "step --policy full --interval 3" \
         "step --policy dots --batch 2" \
         "step --policy none --batch 2" \
         "step --policy none --batch 1"; do
  echo "=== $a"
  timeout 900 python tools/profile_1p3b.py $a 2>&1 | grep -v '^W' | tail -4
done
